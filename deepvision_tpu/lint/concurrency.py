"""jaxsync — lock-discipline, atomicity and deadlock analysis (LCK/THR).

The serving stack is deeply threaded: dispatcher worker pools, the weight
reloader, the promotion controller, the autoscaler, the tier supervisor and
every HTTP handler all mutate shared objects concurrently. The JAX-facing
rules (DON/JIT/TRC/...) understand none of that, and the thread-safety
invariants the stack relies on were enforced only by tests that catch the
races they happen to provoke. This module lifts the whole bug class to lint
time on the same interprocedural CallGraph core the donation pass built:

1.  **Thread-entry index** — every concurrent entrypoint in the project:
    `threading.Thread(target=...)` / `threading.Timer`, executor `submit`,
    and `do_*` methods of `BaseHTTPRequestHandler` subclasses. The reach
    closure over the call graph from those entries is "code that runs on
    more than one thread".

2.  **Lock-guard inference** — per (class, attribute): which lock do the
    accesses sit under? An attribute is *guarded* by lock L when at least
    ``GUARD_RATIO`` of its accesses (reads and writes both count) run with
    L held, at least ``MIN_GUARDED_ACCESSES`` accesses are under L, and at
    least one *write* is under L. ``__init__``/``__new__`` bodies are
    exempt (single-threaded setup), and accesses inside ``*_locked``
    methods of a single-lock class count as guarded — the repo's
    caller-holds-the-lock convention (``_reset_locked``, ``_spawn_locked``,
    ...). Plain reads are NEVER flagged: deliberate lock-free reads of
    monotonic counters are idiomatic here; they merely dilute the guard
    signal. Violations are unguarded WRITES (LCK001) and unguarded
    read-modify-writes (LCK002) in thread-reachable code.

3.  **Lock graph** — lock identities are class-level (``Class.attr`` for
    ``self.attr = threading.Lock()``; one id per class, not per instance,
    so self-edges are ignored). Acquiring M while holding L adds edge
    L -> M, directly or through any resolvable call; a cycle is a
    lock-order deadlock (LCK003). Holding any lock across a blocking
    primitive — socket/HTTP I/O, `subprocess`, `future.result()` /
    `queue.get()` / `join()` / `wait()` without a timeout, `time.sleep` —
    is LCK004, the deadlock shape the tier drain path dodges by hand.

Receiver typing is deliberately conservative: `self` types to the
enclosing class, annotated params (including `Optional[X]` / `Sequence[X]`
element types) and `x = ClassName(...)` locals type to the named project
class, `self.attr` follows the attribute-type table built from
constructor assignments, and attributes assigned by exactly one class
type through that unique owner. A receiver typed to an *external* class
(threading, queue, subprocess, ...) binds to nothing; an untyped receiver
falls back to every project method with that name (conservative union —
safe because findings are gated on guard inference, not on reach alone).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .framework import (Config, Finding, FunctionInfo, Module, dotted_str,
                        terminal_name)

# -- tunables ----------------------------------------------------------------
# "large majority" for guard inference: >= 60% of an attribute's accesses
# under one lock, with a minimum sample so one locked line can't crown a lock
GUARD_RATIO = 0.6
MIN_GUARDED_ACCESSES = 2
# time.sleep under a lock shorter than this is treated as a scheduler nudge,
# not a blocking call (matches the busy-wait poll intervals in the tree)
SLEEP_GUARD_S = 0.01

LOCK_FACTORIES = {"threading.Lock", "threading.RLock", "threading.Condition"}
THREAD_FACTORIES = {"threading.Thread", "threading.Timer"}
EXECUTOR_FACTORIES = {"concurrent.futures.ThreadPoolExecutor",
                      "futures.ThreadPoolExecutor", "ThreadPoolExecutor"}
# constructor prefixes that type a receiver as NOT-a-project-class: calls
# through such receivers bind to no project def (threading.Thread().start()
# must not alias TierRouter.start)
EXTERNAL_PREFIXES = ("threading.", "queue.", "concurrent.", "subprocess.",
                     "socket.", "http.", "urllib.", "logging.", "io.",
                     "collections.", "itertools.", "multiprocessing.")
# builtin constructors that can never return project state: receivers typed
# through them bind to no project method (file.flush() must not alias
# CheckpointManager.flush)
BUILTIN_FACTORIES = {"open", "deque", "defaultdict", "Counter",
                     "OrderedDict", "Event", "Queue", "SimpleQueue",
                     "Semaphore", "BoundedSemaphore", "Barrier", "Popen"}
# single-argument wrappers that preserve their argument's element type
TRANSPARENT_WRAPPERS = {"list", "tuple", "sorted", "reversed", "set",
                        "frozenset", "iter"}
# method names that mutate their receiver in place: x.attr.append(...) is a
# read-modify-write of attr
MUTATORS = {"append", "extend", "add", "update", "insert", "remove",
            "discard", "pop", "popitem", "popleft", "appendleft", "clear",
            "setdefault", "sort"}
# blocking-call prefixes for LCK004 (resolved through import aliases)
BLOCKING_PREFIXES = ("urllib.request.", "http.client.", "socket.",
                     "subprocess.")
SETUP_METHODS = {"__init__", "__new__"}

READ, WRITE, RMW = "read", "write", "rmw"
EXTERNAL = "<external>"


class _Access:
    __slots__ = ("cls", "attr", "kind", "node", "module", "fn", "locks")

    def __init__(self, cls, attr, kind, node, module, fn, locks):
        self.cls = cls          # owning class name
        self.attr = attr        # attribute name
        self.kind = kind        # READ | WRITE | RMW
        self.node = node        # anchor ast node
        self.module = module
        self.fn = fn            # FunctionInfo of the enclosing function
        self.locks = locks      # frozenset of lock ids held at the access


class _CallSite:
    __slots__ = ("call", "module", "fn", "held")

    def __init__(self, call, module, fn, held):
        self.call = call
        self.module = module
        self.fn = fn
        self.held = held        # tuple of lock ids, acquisition-ordered


def _unwrap_annotation(ann: ast.AST, classes: Set[str]) -> Optional[str]:
    """First project class named anywhere in an annotation — handles
    `ReplicaHandle`, `Optional[ModelFleet]`, `Sequence[ReplicaHandle]`,
    string annotations."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    for node in ast.walk(ann):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        if name in classes:
            return name
    return None


class ConcurrencyIndex:
    """Everything the LCK rules consult, built once per lint run from the
    shared CallGraph and memoized in ``ProjectIndex.cache``."""

    def __init__(self, graph):
        self.graph = graph
        self.classes: Set[str] = set()
        self.class_bases: Dict[str, List[str]] = {}
        # class -> lock attribute names (self.x = threading.Lock())
        self.lock_attrs: Dict[str, Set[str]] = {}
        self.lock_owners: Dict[str, Set[str]] = {}   # attr -> classes
        # class -> attr -> class name | EXTERNAL (from ctor assignments)
        self.attr_types: Dict[str, Dict[str, str]] = {}
        # attr -> classes that self-assign it outside lock factories
        self.attr_owners: Dict[str, Set[str]] = {}
        self.accesses: List[_Access] = []
        self.call_sites: List[_CallSite] = []
        # (lock_id, held_before, node, module) per `with <lock>:`
        self.acquisitions: List[Tuple[str, Tuple[str, ...], ast.AST,
                                      Module]] = []
        self.entries: Dict[int, str] = {}      # id(fn node) -> entry label
        self.reach: Dict[int, str] = {}        # id(fn node) -> entry label
        self.guards: Dict[Tuple[str, str], Tuple[str, int, int]] = {}
        self.acquires: Dict[int, Set[str]] = {}
        self.blocking: Dict[int, str] = {}
        # rule -> list of (module, node, message)
        self.violations: Dict[str, List[Tuple[Module, ast.AST, str]]] = {}
        self._infos: List[FunctionInfo] = []
        self._local_cache: Dict[int, Dict[str, str]] = {}
        self._build()

    # -- construction --------------------------------------------------------

    def _build(self) -> None:
        seen: Set[int] = set()
        for info in self.graph.info_of.values():
            if id(info.node) not in seen:
                seen.add(id(info.node))
                self._infos.append(info)
        for module in self.graph.modules:
            self._scan_classes(module)
        for module in self.graph.modules:
            self._scan_attr_types(module)
        for info in self._infos:
            self._walk_fn(info)
        self._infer_guards()
        self._find_entries()
        self._compute_reach()
        self._fix_acquires()
        self._fix_blocking()
        self._collect_violations()

    def _scan_classes(self, module: Module) -> None:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            self.classes.add(node.name)
            self.class_bases[node.name] = [
                terminal_name(b) for b in node.bases if terminal_name(b)]
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Assign)
                        and isinstance(sub.value, ast.Call)):
                    continue
                resolved = module.resolve(sub.value.func)
                if resolved not in LOCK_FACTORIES:
                    continue
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name):
                        self.lock_attrs.setdefault(node.name, set()).add(
                            tgt.attr)
                        self.lock_owners.setdefault(tgt.attr, set()).add(
                            node.name)

    def _scan_attr_types(self, module: Module) -> None:
        """self.attr = <expr> assignments whose type is statically evident:
        a project-class constructor, an external-library constructor, an
        annotated parameter, or a self-method call returning `Cls(...)`."""
        for cls_node in ast.walk(module.tree):
            if not isinstance(cls_node, ast.ClassDef):
                continue
            table = self.attr_types.setdefault(cls_node.name, {})
            for fn in cls_node.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                self_arg = fn.args.args[0].arg if fn.args.args else None
                ann_of = {a.arg: a.annotation
                          for a in fn.args.args + fn.args.kwonlyargs
                          if a.annotation is not None}
                for stmt in ast.walk(fn):
                    tgt = value = None
                    if isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1:
                        tgt, value = stmt.targets[0], stmt.value
                    elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                        tgt, value = stmt.target, stmt.value
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == self_arg):
                        continue
                    if tgt.attr not in self.lock_attrs.get(cls_node.name,
                                                           ()):
                        self.attr_owners.setdefault(tgt.attr, set()).add(
                            cls_node.name)
                    typ = self._type_of_value(module, cls_node.name, value,
                                              ann_of)
                    if typ is None and isinstance(stmt, ast.AnnAssign):
                        typ = _unwrap_annotation(stmt.annotation,
                                                 self.classes)
                    if typ is not None:
                        prev = table.get(tgt.attr)
                        if prev is not None and prev != typ:
                            table[tgt.attr] = EXTERNAL  # ambiguous: no bind
                        else:
                            table[tgt.attr] = typ

    def _type_of_value(self, module, cls, value, ann_of):
        if isinstance(value, ast.Name):
            return _unwrap_annotation(ann_of.get(value.id), self.classes)
        if not isinstance(value, ast.Call):
            return None
        term = terminal_name(value.func)
        # list(replicas) et al. carry their argument's (element) type
        if term in TRANSPARENT_WRAPPERS and len(value.args) == 1:
            return self._type_of_value(module, cls, value.args[0], ann_of)
        resolved = module.resolve(value.func)
        if resolved and resolved.startswith(EXTERNAL_PREFIXES):
            return EXTERNAL
        if term in self.classes:
            return term
        if term in BUILTIN_FACTORIES:
            return EXTERNAL
        # one hop through a factory: self.breaker = self._fresh_breaker()
        # types through its `return CircuitBreaker(...)`; a factory whose
        # returns are all non-project (tf writers, file handles) types
        # EXTERNAL so its receiver binds to no project method
        callee = None
        if isinstance(value.func, ast.Attribute) \
                and isinstance(value.func.value, ast.Name):
            callee = self.graph.methods.get(cls, {}).get(value.func.attr)
        if callee is None:
            cands = self.graph.resolve_call(module, value)
            callee = cands[0] if len(cands) == 1 else None
        if callee is not None:
            votes: Set[Optional[str]] = set()
            for ret in ast.walk(callee.node):
                if not isinstance(ret, ast.Return) or ret.value is None:
                    continue
                if isinstance(ret.value, ast.Constant):
                    continue
                if isinstance(ret.value, ast.Call):
                    rterm = terminal_name(ret.value.func)
                    votes.add(rterm if rterm in self.classes else EXTERNAL)
                else:
                    votes.add(None)  # untypable return: stay unknown
            project = {v for v in votes if v not in (None, EXTERNAL)}
            if len(project) == 1:
                return next(iter(project))
            if votes and votes == {EXTERNAL}:
                return EXTERNAL
        return None

    # -- receiver typing -----------------------------------------------------

    def _receiver_type(self, info: FunctionInfo, expr: ast.AST,
                       local_types: Dict[str, str]) -> Optional[str]:
        """Class name, EXTERNAL, or None (unknown) for a receiver expr."""
        if isinstance(expr, ast.Name):
            if info.cls_name and info.params \
                    and expr.id == info.params[0] \
                    and info.params[0] in ("self", "cls"):
                return info.cls_name
            return local_types.get(expr.id)
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            base = self._receiver_type(info, expr.value, local_types)
            if base in self.attr_types:
                return self.attr_types[base].get(expr.attr)
            return None
        if isinstance(expr, ast.Call):
            term = terminal_name(expr.func)
            if term in self.classes:
                return term
        return None

    def _local_types(self, info: FunctionInfo) -> Dict[str, str]:
        """Parameter annotations + `x = ClassName(...)` locals +
        `with ThreadPoolExecutor() as p` with-items. Memoized per fn."""
        got = self._local_cache.get(id(info.node))
        if got is not None:
            return got
        out: Dict[str, str] = {}
        self._local_cache[id(info.node)] = out
        fn = info.node
        args = getattr(fn, "args", None)
        if args is not None:
            for a in args.posonlyargs + args.args + args.kwonlyargs:
                typ = _unwrap_annotation(a.annotation, self.classes)
                if typ:
                    out[a.arg] = typ
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                typ = self._ctor_type(info.module, node.value)
                if typ:
                    out[node.targets[0].id] = typ
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.optional_vars, ast.Name) \
                            and isinstance(item.context_expr, ast.Call):
                        typ = self._ctor_type(info.module,
                                              item.context_expr)
                        if typ:
                            out[item.optional_vars.id] = typ
        # second pass: for-loop / comprehension targets type through their
        # iterable (`for h in self.replicas:` -> ReplicaHandle)
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.comprehension)):
                tgt = node.target
                if isinstance(tgt, ast.Name) and tgt.id not in out:
                    typ = self._element_type(info, node.iter, out)
                    if typ:
                        out[tgt.id] = typ
        return out

    def _element_type(self, info, expr, local_types) -> Optional[str]:
        """Element type of an iterated expression, best-effort."""
        if isinstance(expr, ast.Call) \
                and terminal_name(expr.func) in TRANSPARENT_WRAPPERS \
                and len(expr.args) == 1:
            return self._element_type(info, expr.args[0], local_types)
        if isinstance(expr, ast.Subscript):  # replicas[k:] slices
            return self._element_type(info, expr.value, local_types)
        if isinstance(expr, ast.BinOp):      # replicas[k:] + replicas[:k]
            return (self._element_type(info, expr.left, local_types)
                    or self._element_type(info, expr.right, local_types))
        typ = self._receiver_type(info, expr, local_types)
        if typ is None or typ.startswith("<"):
            return None
        # iterating a project class hops through its __iter__ -> Iterator[X]
        it = self.graph.methods.get(typ, {}).get("__iter__")
        if it is not None:
            elem = _unwrap_annotation(getattr(it.node, "returns", None),
                                      self.classes)
            if elem:
                return elem
        return typ

    def _ctor_type(self, module, call: ast.Call) -> Optional[str]:
        term = terminal_name(call.func)
        if term in self.classes:
            return term
        resolved = module.resolve(call.func)
        if resolved and resolved.startswith(EXTERNAL_PREFIXES):
            if resolved in EXECUTOR_FACTORIES or \
                    (resolved or "").endswith("ThreadPoolExecutor"):
                return "<executor>"
            return EXTERNAL
        return None

    # -- per-function walk ---------------------------------------------------

    def _lock_id(self, info, expr, local_types) -> Optional[str]:
        """Lock identity for `with <expr>:` — Class.attr for attribute
        locks (typed receiver, else unique owner), module/local names for
        bare `with lock:`."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            rtype = self._receiver_type(info, expr.value, local_types)
            if rtype == EXTERNAL:
                return None
            if rtype and attr in self.lock_attrs.get(rtype, ()):
                return f"{rtype}.{attr}"
            if len(self.lock_owners.get(attr, ())) == 1:
                return f"{next(iter(self.lock_owners[attr]))}.{attr}"
            return None
        if isinstance(expr, ast.Name) \
                and expr.id in self._module_locks(info.module):
            return f"{info.module.path}::{expr.id}"
        return None

    def _module_locks(self, module: Module) -> Set[str]:
        got = getattr(module, "_jaxsync_module_locks", None)
        if got is None:
            got = set()
            for stmt in module.tree.body:
                if isinstance(stmt, ast.Assign) \
                        and isinstance(stmt.value, ast.Call) \
                        and module.resolve(stmt.value.func) in LOCK_FACTORIES:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            got.add(tgt.id)
            module._jaxsync_module_locks = got
        return got

    def _walk_fn(self, info: FunctionInfo) -> None:
        fn = info.node
        if isinstance(fn, ast.Lambda):
            return
        local_types = self._local_types(info)
        # the caller-holds-the-lock convention: a *_locked method of a
        # class with exactly one lock runs entirely under that lock
        base_held: Tuple[str, ...] = ()
        if info.cls_name and fn.name.endswith("_locked") \
                and len(self.lock_attrs.get(info.cls_name, ())) == 1:
            only = next(iter(self.lock_attrs[info.cls_name]))
            base_held = (f"{info.cls_name}.{only}",)
        self._visit_block(info, fn.body, base_held, local_types)

    def _visit_block(self, info, stmts, held, local_types) -> None:
        for stmt in stmts:
            self._visit_stmt(info, stmt, held, local_types)

    def _visit_stmt(self, info, stmt, held, local_types) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate scope: walked via its own FunctionInfo
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered: List[str] = []
            for item in stmt.items:
                self._visit_expr(info, item.context_expr, held, local_types)
                lock = self._lock_id(info, item.context_expr, local_types)
                if lock is not None:
                    self.acquisitions.append(
                        (lock, tuple(held), item.context_expr, info.module))
                    entered.append(lock)
            self._visit_block(info, stmt.body, tuple(held) + tuple(entered),
                              local_types)
            return
        for field in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, field, []) or []:
                self._visit_stmt(info, sub, held, local_types)
        for handler in getattr(stmt, "handlers", []) or []:
            self._visit_block(info, handler.body, held, local_types)
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            # one walk over the whole statement so the store target and the
            # value reads share RMW folding
            self._visit_expr(info, stmt, held, local_types,
                             parent_stmt=stmt)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.stmt, ast.excepthandler)):
                continue
            self._visit_expr(info, child, held, local_types,
                             parent_stmt=stmt)

    def _attr_class(self, info, node: ast.Attribute, local_types):
        """Owning class for an attribute access, or None when untypable."""
        rtype = self._receiver_type(info, node.value, local_types)
        if rtype is not None and rtype.startswith("<"):
            return None  # external / executor: never project state
        if rtype in self.classes:
            return rtype
        owners = self.attr_owners.get(node.attr, ())
        if len(owners) == 1:
            return next(iter(owners))
        return None

    def _record(self, info, node, kind, held, local_types) -> None:
        cls = self._attr_class(info, node, local_types)
        if cls is None:
            return
        self.accesses.append(_Access(cls, node.attr, kind, node,
                                     info.module, info, frozenset(held)))

    def _visit_expr(self, info, expr, held, local_types,
                    parent_stmt=None) -> None:
        """Record attribute accesses and call sites in an expression tree.
        `parent_stmt` classifies stores (Assign/AugAssign targets)."""
        skip: Set[int] = set()
        # `R.x = f(R.x)` / `R.x += v` is ONE logical read-modify-write: the
        # store is the RMW, and reads of the same spelling in the value
        # expression fold into it instead of counting separately
        rmw_spellings: Set[Tuple[str, str]] = set()
        for node in ast.walk(expr):
            if id(node) in skip:
                continue
            if isinstance(node, ast.Call):
                self.call_sites.append(_CallSite(node, info.module, info,
                                                 tuple(held)))
                # x.attr.append(v) — in-place mutation of attr
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in MUTATORS \
                        and isinstance(f.value, ast.Attribute):
                    self._record(info, f.value, RMW, held, local_types)
                    skip.add(id(f.value))
            elif isinstance(node, ast.Attribute):
                ctx = node.ctx
                if isinstance(ctx, ast.Load):
                    spelled = (dotted_str(node.value), node.attr)
                    if spelled not in rmw_spellings:
                        self._record(info, node, READ, held, local_types)
                elif isinstance(ctx, (ast.Store, ast.Del)):
                    kind = WRITE
                    if isinstance(parent_stmt, ast.AugAssign):
                        kind = RMW
                    elif isinstance(parent_stmt, ast.Assign) \
                            and self._reads_same_attr(info, parent_stmt,
                                                      node, local_types):
                        kind = RMW
                        rmw_spellings.add((dotted_str(node.value),
                                           node.attr))
                    self._record(info, node, kind, held, local_types)
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)) \
                    and isinstance(node.value, ast.Attribute):
                # d[k] = v on a shared dict/list attribute
                self._record(info, node.value, RMW, held, local_types)
                skip.add(id(node.value))

    def _reads_same_attr(self, info, assign: ast.Assign,
                         target: ast.Attribute, local_types) -> bool:
        """`R.x = f(R.x)` — an Assign whose value reads the stored attr is
        one logical read-modify-write, not an independent read + write."""
        want = (dotted_str(target.value), target.attr)
        if want[0] is None:
            return False
        for node in ast.walk(assign.value):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.attr == want[1] \
                    and dotted_str(node.value) == want[0]:
                return True
        return False

    # -- guard inference -----------------------------------------------------

    def _infer_guards(self) -> None:
        stats: Dict[Tuple[str, str], Dict[str, object]] = {}
        for acc in self.accesses:
            if acc.fn.node.name in SETUP_METHODS \
                    or acc.attr in self.lock_attrs.get(acc.cls, ()):
                continue
            st = stats.setdefault((acc.cls, acc.attr),
                                  {"total": 0, "by_lock": {}})
            st["total"] += 1
            for lock in acc.locks:
                st["by_lock"][lock] = st["by_lock"].get(lock, 0) + 1
        for key, st in stats.items():
            if not st["by_lock"]:
                continue
            lock, count = max(st["by_lock"].items(),
                              key=lambda kv: (kv[1], kv[0]))
            # note: no guarded-WRITE requirement — stripping the lock from
            # the sole writing site must not erase the guard the remaining
            # locked reads still witness (violations are writes/RMWs in
            # thread-reachable code, so read-only guarded attrs stay silent)
            if count >= MIN_GUARDED_ACCESSES \
                    and count / st["total"] >= GUARD_RATIO:
                self.guards[key] = (lock, count, st["total"])

    # -- thread entries and reach --------------------------------------------

    def _resolve_target(self, module, info, target,
                        local_types) -> List[FunctionInfo]:
        """FunctionInfos a Thread/submit target expression may name."""
        if isinstance(target, ast.Name):
            local = [i for i in self.graph.defs.get(target.id, [])
                     if i.module is module]
            # imported target: every project def with that name (union)
            return local or self.graph.defs.get(target.id, [])
        if isinstance(target, ast.Attribute):
            rtype = self._receiver_type(info, target.value, local_types)
            if rtype in self.graph.methods:
                got = self.graph.methods[rtype].get(target.attr)
                return [got] if got else []
            if rtype is None:
                return [m[target.attr] for m in self.graph.methods.values()
                        if target.attr in m]
        if isinstance(target, ast.Lambda):
            pass  # lambda bodies hold no attribute state worth tracking
        return []

    def _find_entries(self) -> None:
        for site in self.call_sites:
            call, module, info = site.call, site.module, site.fn
            local_types = self._local_types(info)
            resolved = module.resolve(call.func)
            targets: List[FunctionInfo] = []
            label = None
            if resolved in THREAD_FACTORIES:
                label = f"{resolved}(target=...) in {info.qualname}"
                tgt = None
                for kw in call.keywords:
                    if kw.arg in ("target", "function"):
                        tgt = kw.value
                if tgt is None and len(call.args) > 1:
                    tgt = call.args[1]
                if tgt is not None:
                    targets = self._resolve_target(module, info, tgt,
                                                   local_types)
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "submit" and call.args:
                rtype = self._receiver_type(info, call.func.value,
                                            local_types)
                if rtype in (None, "<executor>"):
                    label = f"executor.submit in {info.qualname}"
                    targets = self._resolve_target(module, info,
                                                   call.args[0],
                                                   local_types)
            for t in targets:
                self.entries.setdefault(id(t.node), label)
        # HTTP handler methods: do_* of BaseHTTPRequestHandler subclasses
        # (transitively, by terminal base name within the project)
        handler_classes: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for cls, bases in self.class_bases.items():
                if cls in handler_classes:
                    continue
                if any(b == "BaseHTTPRequestHandler" or b in handler_classes
                       for b in bases):
                    handler_classes.add(cls)
                    changed = True
        for cls in handler_classes:
            for name, meth in self.graph.methods.get(cls, {}).items():
                if name.startswith("do_"):
                    self.entries.setdefault(
                        id(meth.node), f"HTTP handler {cls}.{name}")

    def _callees(self, site: _CallSite) -> List[FunctionInfo]:
        call, module, info = site.call, site.module, site.fn
        got = self.graph.resolve_call(module, call)
        if got:
            return got
        if isinstance(call.func, ast.Attribute):
            local_types = self._local_types(info)
            rtype = self._receiver_type(info, call.func.value, local_types)
            if rtype in self.graph.methods:
                m = self.graph.methods[rtype].get(call.func.attr)
                return [m] if m else []
            if rtype is None:
                name = call.func.attr
                if name.startswith("__"):
                    return []
                return [m[name] for m in self.graph.methods.values()
                        if name in m]
        return []

    def _sites_of(self) -> Dict[int, List[_CallSite]]:
        got: Dict[int, List[_CallSite]] = {}
        for site in self.call_sites:
            got.setdefault(id(site.fn.node), []).append(site)
        return got

    def _compute_reach(self) -> None:
        sites = self._sites_of()
        work = list(self.entries.items())
        self.reach = dict(self.entries)
        while work:
            fn_id, label = work.pop()
            for site in sites.get(fn_id, ()):
                for callee in self._callees(site):
                    if id(callee.node) not in self.reach:
                        self.reach[id(callee.node)] = label
                        work.append((id(callee.node), label))

    # -- lock graph + blocking fixpoints -------------------------------------

    def _fix_acquires(self) -> None:
        direct: Dict[int, Set[str]] = {id(i.node): set()
                                       for i in self._infos}
        for lock, _held, node, module in self.acquisitions:
            owner = self._fn_of_node(module, node)
            if owner is not None:
                direct.setdefault(id(owner.node), set()).add(lock)
        self.acquires = {k: set(v) for k, v in direct.items()}
        sites = self._sites_of()
        changed = True
        while changed:
            changed = False
            for info in self._infos:
                mine = self.acquires.setdefault(id(info.node), set())
                for site in sites.get(id(info.node), ()):
                    for callee in self._callees(site):
                        extra = self.acquires.get(id(callee.node), ())
                        for lock in extra:
                            if lock not in mine:
                                mine.add(lock)
                                changed = True

    def _fn_of_node(self, module: Module,
                    node: ast.AST) -> Optional[FunctionInfo]:
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self.graph.info(cur)
            cur = module.parent(cur)
        return None

    def _blocking_primitive(self, module: Module,
                            call: ast.Call) -> Optional[str]:
        resolved = module.resolve(call.func)
        if resolved:
            if resolved == "time.sleep":
                val = call.args[0] if call.args else None
                if isinstance(val, ast.Constant) \
                        and isinstance(val.value, (int, float)):
                    if val.value >= SLEEP_GUARD_S:
                        return f"time.sleep({val.value})"
                    return None
                return "time.sleep(...)"
            if resolved.startswith(BLOCKING_PREFIXES):
                return f"{resolved}(...) [I/O]"
        f = call.func
        if isinstance(f, ast.Attribute) and not call.args:
            kwargs = {kw.arg for kw in call.keywords}
            if f.attr == "result" and "timeout" not in kwargs:
                return "future.result() without a timeout"
            if f.attr == "get" and not kwargs:
                return "queue.get() without a timeout"
            if f.attr == "join" and "timeout" not in kwargs:
                return "join() without a timeout"
            if f.attr == "wait" and "timeout" not in kwargs:
                return "wait() without a timeout"
        return None

    def _fix_blocking(self) -> None:
        sites = self._sites_of()
        for info in self._infos:
            for site in sites.get(id(info.node), ()):
                reason = self._blocking_primitive(info.module, site.call)
                if reason and id(info.node) not in self.blocking:
                    self.blocking[id(info.node)] = reason
        changed = True
        while changed:
            changed = False
            for info in self._infos:
                if id(info.node) in self.blocking:
                    continue
                for site in sites.get(id(info.node), ()):
                    for callee in self._callees(site):
                        reason = self.blocking.get(id(callee.node))
                        if reason:
                            self.blocking[id(info.node)] = \
                                f"calls {callee.qualname}: {reason}"
                            changed = True
                            break
                    if id(info.node) in self.blocking:
                        break

    # -- violations ----------------------------------------------------------

    def _emit(self, rule, module, node, message) -> None:
        self.violations.setdefault(rule, []).append((module, node, message))

    def _collect_violations(self) -> None:
        # LCK001 / LCK002: unguarded write / RMW on a guarded attribute in
        # thread-reachable code
        for acc in self.accesses:
            if acc.kind == READ or acc.fn.node.name in SETUP_METHODS:
                continue
            guard = self.guards.get((acc.cls, acc.attr))
            if guard is None:
                continue
            lock, count, total = guard
            if lock in acc.locks:
                continue
            entry = self.reach.get(id(acc.fn.node))
            if entry is None:
                continue
            where = f"{acc.cls}.{acc.attr}"
            how = (f"guarded by {lock} ({count} of {total} accesses) but "
                   f"this {'read-modify-write' if acc.kind == RMW else 'write'} "
                   f"in {acc.fn.qualname} runs outside it; "
                   f"thread-reachable via {entry}")
            rule = "LCK002" if acc.kind == RMW else "LCK001"
            self._emit(rule, acc.module, acc.node, f"{where} is {how}")

        # LCK003: lock-order cycles over the acquisition graph
        edges: Dict[str, Dict[str, Tuple[ast.AST, Module]]] = {}

        def add_edge(a, b, node, module):
            if a != b and b not in edges.setdefault(a, {}):
                edges[a][b] = (node, module)

        for lock, held, node, module in self.acquisitions:
            for h in held:
                add_edge(h, lock, node, module)
        sites = self._sites_of()
        for info in self._infos:
            for site in sites.get(id(info.node), ()):
                if not site.held:
                    continue
                for callee in self._callees(site):
                    for lock in self.acquires.get(id(callee.node), ()):
                        for h in site.held:
                            add_edge(h, lock, site.call, site.module)
        for cycle in self._cycles(edges):
            a, b = cycle[0], cycle[1 % len(cycle)]
            node, module = edges[a][b]
            path = " -> ".join(cycle + (cycle[0],))
            self._emit("LCK003", module, node,
                       f"lock-order cycle {path}: two threads acquiring "
                       f"these locks in opposite orders can deadlock")

        # LCK004: blocking call while holding a lock
        for info in self._infos:
            for site in sites.get(id(info.node), ()):
                if not site.held:
                    continue
                reason = self._blocking_primitive(site.module, site.call)
                if reason is None:
                    for callee in self._callees(site):
                        sub = self.blocking.get(id(callee.node))
                        if sub:
                            reason = f"calls {callee.qualname}: {sub}"
                            break
                if reason:
                    self._emit(
                        "LCK004", site.module, site.call,
                        f"blocking call while holding "
                        f"{', '.join(site.held)}: {reason} — any thread "
                        f"needing the lock stalls behind this call")

    def _cycles(self, edges) -> List[Tuple[str, ...]]:
        """Elementary cycles, canonicalized (rotated to min node, deduped).
        The lock graphs here are tiny; simple DFS is plenty."""
        out: Set[Tuple[str, ...]] = set()
        for start in sorted(edges):
            stack = [(start, (start,))]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(edges.get(node, ())):
                    if nxt == path[0] and len(path) > 1:
                        i = path.index(min(path))
                        out.add(path[i:] + path[:i])
                    elif nxt not in path and len(path) < 8:
                        stack.append((nxt, path + (nxt,)))
        return sorted(out)


# -- index memoization -------------------------------------------------------

def concurrency_index(index) -> ConcurrencyIndex:
    cache = getattr(index, "cache", None)
    if isinstance(cache, dict):
        got = cache.get("concurrency")
        if isinstance(got, ConcurrencyIndex):
            return got
    built = ConcurrencyIndex(index.graph)
    if isinstance(cache, dict):
        cache["concurrency"] = built
    return built


def _emit_for(module: Module, index, config: Config, rule: str,
              severity: str) -> List[Finding]:
    if getattr(index, "graph", None) is None:
        return []  # index not built (unit-style invocation): nothing global
    conc = concurrency_index(index)
    findings = []
    for mod, node, message in conc.violations.get(rule, ()):
        if mod.path != module.path:
            continue
        f = module.finding(node, rule, severity, message)
        if f is not None:
            findings.append(f)
    findings.sort(key=lambda f: (f.line, f.col))
    return findings


# -- the rules ---------------------------------------------------------------

def check_lck001(module: Module, index, config: Config) -> List[Finding]:
    return _emit_for(module, index, config, "LCK001", "error")


def check_lck002(module: Module, index, config: Config) -> List[Finding]:
    return _emit_for(module, index, config, "LCK002", "error")


def check_lck003(module: Module, index, config: Config) -> List[Finding]:
    return _emit_for(module, index, config, "LCK003", "error")


def check_lck004(module: Module, index, config: Config) -> List[Finding]:
    return _emit_for(module, index, config, "LCK004", "warning")


def check_thr001(module: Module, index, config: Config) -> List[Finding]:
    """Thread created with neither daemon=True nor a reachable join: on
    interpreter shutdown a forgotten non-daemon worker hangs the process —
    the library must either mark threads daemon or own their lifecycle.
    Purely intra-module: handle spellings are tracked through one level of
    aliasing (`threads = list(self._threads)` ... `t.join()`)."""
    findings: List[Finding] = []
    joined = _joined_spellings(module)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if module.resolve(node.func) not in THREAD_FACTORIES:
            continue
        daemon = None
        for kw in node.keywords:
            if kw.arg == "daemon":
                daemon = kw.value
        if daemon is not None:
            # daemon=True is the fix; a non-constant daemon flag gets the
            # benefit of the doubt (caller-controlled lifecycle)
            if not (isinstance(daemon, ast.Constant)
                    and daemon.value is False):
                continue
        if _handles_of(module, node) & joined:
            continue
        f = module.finding(
            node, "THR001", "warning",
            "thread started with neither daemon=True nor a reachable "
            "join(): a forgotten non-daemon worker hangs interpreter "
            "shutdown — mark it daemon or own its lifecycle")
        if f is not None:
            findings.append(f)
    return findings


def _handles_of(module: Module, creation: ast.Call) -> Set[str]:
    """Every spelling the created thread object is bound to: assignment
    targets, list-literal/ comprehension targets, containers it is
    appended to, and later re-bindings of a bare name handle."""
    out: Set[str] = set()
    stmt = module.statement_of(creation)
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            spelled = dotted_str(tgt)
            if spelled:
                out.add(spelled)
    # container.append(Thread(...)) — the container is the handle
    for anc in module.ancestors(creation):
        if isinstance(anc, ast.Call) \
                and isinstance(anc.func, ast.Attribute) \
                and anc.func.attr == "append":
            spelled = dotted_str(anc.func.value)
            if spelled:
                out.add(spelled)
    # propagate bare-name handles forward one step within the scope:
    # `self._threads.append(t)`, `pool[i] = t`, `threads = [t, ...]`
    scope = module.enclosing_scope(creation)
    names = {s for s in out if "." not in s and "::" not in s}
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "append" and node.args \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in names:
            spelled = dotted_str(node.func.value)
            if spelled:
                out.add(spelled)
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in names:
            for tgt in node.targets:
                spelled = dotted_str(tgt)
                if spelled:
                    out.add(spelled)
    return out


def _joined_spellings(module: Module) -> Set[str]:
    """Spellings that reach a join() somewhere in the module, expanded one
    aliasing level (`snapshot = list(self._threads)` joins the original)."""
    joined: Set[str] = set()
    aliases: Dict[str, Set[str]] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join":
            spelled = dotted_str(node.func.value)
            if spelled:
                joined.add(spelled)
        elif isinstance(node, (ast.For, ast.comprehension)):
            iter_expr = node.iter
            # unwrap list(X) / sorted(X) / reversed(X)
            if isinstance(iter_expr, ast.Call) and len(iter_expr.args) == 1:
                iter_expr = iter_expr.args[0]
            src = dotted_str(iter_expr)
            tgt = dotted_str(getattr(node, "target", None))
            if src and tgt:
                aliases.setdefault(tgt, set()).add(src)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            value = node.value
            if isinstance(value, ast.Call) and len(value.args) == 1:
                value = value.args[0]
            src = dotted_str(value)
            tgt = dotted_str(node.targets[0])
            if src and tgt:
                aliases.setdefault(tgt, set()).add(src)
    changed = True
    while changed:
        changed = False
        for tgt, srcs in aliases.items():
            if tgt in joined:
                for src in srcs:
                    if src not in joined:
                        joined.add(src)
                        changed = True
    return joined
