"""jaxlint plumbing: findings, suppressions, config, and AST utilities.

Everything here is stdlib-only — the linter must run (and run fast) on hosts
with no jax installed, and importing jax would drag backend init into what is
a pure source-level pass.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

# scopes that cut off name visibility / execution locality for our analyses
SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    severity: str
    message: str

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# -- inline suppression ------------------------------------------------------
# `# jaxlint: disable=DON001[,SYNC001]` on the flagged line suppresses those
# rules there; `# jaxlint: disable-file=RULE` anywhere suppresses file-wide.
_DIRECTIVE_RE = re.compile(
    r"#\s*jaxlint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_*,\s]+)")


def parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for lineno, line in enumerate(source.splitlines(), 1):
        m = _DIRECTIVE_RE.search(line)
        if not m:
            continue
        rules = {r.strip().upper() for r in m.group(2).split(",") if r.strip()}
        if m.group(1) == "disable-file":
            file_wide |= rules
        else:
            per_line.setdefault(lineno, set()).update(rules)
            if line.lstrip().startswith("#"):
                # a comment-only directive line also covers the next line,
                # so suppressions fit an 79-col style
                per_line.setdefault(lineno + 1, set()).update(rules)
    return per_line, file_wide


# -- config ------------------------------------------------------------------
@dataclasses.dataclass
class Config:
    """`[tool.jaxlint]` in pyproject.toml. All keys optional."""
    exclude: Tuple[str, ...] = ()          # path globs / directory prefixes
    disable: Tuple[str, ...] = ()          # rule ids disabled project-wide
    hot_loop_callees: Tuple[str, ...] = () # extra callee names marking a loop hot
    sync_allowed_guards: Tuple[str, ...] = ()  # extra guard-name patterns
    # declared policy dtype for the DTY rules ("bfloat16"/"float16"); empty
    # string means no declared policy and DTY001 stays off
    compute_dtype: str = ""

    def rule_enabled(self, rule_id: str) -> bool:
        return rule_id.upper() not in {r.upper() for r in self.disable}

    def is_excluded(self, path: str, root: str) -> bool:
        rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
        for pat in self.exclude:
            pat = pat.rstrip("/")
            if (fnmatch.fnmatch(rel, pat) or fnmatch.fnmatch(rel, pat + "/*")
                    or rel == pat or rel.startswith(pat + "/")):
                return True
        return False


def _split_inline_comment(line: str) -> str:
    """Drop a trailing `# ...` comment, respecting simple quoted strings."""
    quote = None
    for i, ch in enumerate(line):
        if quote:
            if ch == quote:
                quote = None
        elif ch in ("'", '"'):
            quote = ch
        elif ch == "#":
            return line[:i]
    return line


def _parse_toml_value(text: str):
    text = text.strip()
    if text.startswith("[") and text.endswith("]"):
        body = text[1:-1]
        items, cur, quote = [], "", None
        for ch in body:
            if quote:
                cur += ch
                if ch == quote:
                    quote = None
            elif ch in ("'", '"'):
                quote = ch
                cur += ch
            elif ch == ",":
                items.append(cur)
                cur = ""
            else:
                cur += ch
        if cur.strip():
            items.append(cur)
        return [_parse_toml_value(i) for i in items if i.strip()]
    if (text.startswith('"') and text.endswith('"')) or (
            text.startswith("'") and text.endswith("'")):
        return text[1:-1]
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        return text


def parse_tool_section(source: str, section: str = "tool.jaxlint") -> dict:
    """Minimal TOML-subset reader for one `[section]` of pyproject.toml.

    Python 3.10 has no stdlib tomllib and jaxlint adds no dependencies, so
    this handles exactly what the section needs: string / bool / int values
    and (possibly multi-line) arrays of strings. Unknown shapes are ignored.
    """
    out: dict = {}
    in_section = False
    pending_key: Optional[str] = None
    pending_val = ""
    for raw in source.splitlines():
        line = _split_inline_comment(raw).rstrip()
        stripped = line.strip()
        if pending_key is not None:
            pending_val += " " + stripped
            if pending_val.count("[") <= pending_val.count("]"):
                out[pending_key] = _parse_toml_value(pending_val)
                pending_key, pending_val = None, ""
            continue
        if stripped.startswith("["):
            in_section = stripped == f"[{section}]"
            continue
        if not in_section or "=" not in stripped:
            continue
        key, _, val = stripped.partition("=")
        key, val = key.strip().strip('"').strip("'"), val.strip()
        if val.count("[") > val.count("]"):
            pending_key, pending_val = key, val
            continue
        out[key] = _parse_toml_value(val)
    return out


def find_pyproject(start: str) -> Optional[str]:
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        cand = os.path.join(cur, "pyproject.toml")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def load_config(pyproject_path: Optional[str]) -> Config:
    if not pyproject_path or not os.path.isfile(pyproject_path):
        return Config()
    with open(pyproject_path, encoding="utf-8") as fp:
        raw = parse_tool_section(fp.read())

    def strings(key) -> Tuple[str, ...]:
        val = raw.get(key, [])
        if isinstance(val, str):
            val = [val]
        return tuple(str(v) for v in val if isinstance(v, (str, int)))

    compute_dtype = raw.get("compute-dtype", "")
    return Config(exclude=strings("exclude"),
                  disable=strings("disable"),
                  hot_loop_callees=strings("hot-loop-callees"),
                  sync_allowed_guards=strings("sync-allowed-guards"),
                  compute_dtype=(compute_dtype
                                 if isinstance(compute_dtype, str) else ""))


# -- AST module context ------------------------------------------------------
def dotted_parts(node: ast.AST) -> Optional[List[str]]:
    """`a.b.c` -> ["a", "b", "c"]; None for anything not a pure name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def dotted_str(node: ast.AST) -> Optional[str]:
    parts = dotted_parts(node)
    return ".".join(parts) if parts else None


def terminal_name(node: ast.AST) -> Optional[str]:
    """Last segment of a callee: `steps.make_yolo_train_step` -> the latter."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def walk_scope(scope: ast.AST) -> Iterator[ast.AST]:
    """Yield nodes belonging to `scope`, NOT descending into nested function
    scopes (the nested defs themselves are yielded, their bodies are not).
    Comprehensions are treated as part of the enclosing scope."""
    stack = list(ast.iter_child_nodes(scope))[::-1]
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, SCOPE_TYPES):
            stack.extend(list(ast.iter_child_nodes(node))[::-1])


class Module:
    """One parsed file plus the cross-referencing helpers rules need."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[id(child)] = parent
        self.aliases, self.import_roots = self._collect_aliases()
        self.line_suppress, self.file_suppress = parse_suppressions(source)
        self._scope_defs: Dict[int, Dict[str, ast.AST]] = {}

    @classmethod
    def from_path(cls, path: str) -> "Module":
        with open(path, encoding="utf-8") as fp:
            return cls(path, fp.read())

    def _collect_aliases(self) -> Tuple[Dict[str, str], Set[str]]:
        aliases: Dict[str, str] = {}
        roots: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    aliases[local] = a.name if a.asname else a.name.split(".")[0]
                    roots.add(local)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    aliases[local] = f"{node.module}.{a.name}"
                    roots.add(local)
        return aliases, roots

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_scope(self, node: ast.AST) -> ast.AST:
        for anc in self.ancestors(node):
            if isinstance(anc, SCOPE_TYPES) or isinstance(anc, ast.Module):
                return anc
        return self.tree

    def statement_of(self, node: ast.AST) -> ast.stmt:
        cur = node
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.Module, *SCOPE_TYPES)) or isinstance(
                    anc, (ast.If, ast.For, ast.While, ast.With, ast.Try)):
                if isinstance(cur, ast.stmt):
                    return cur
            cur = anc
        return cur if isinstance(cur, ast.stmt) else node  # pragma: no cover

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Callee dotted path with import aliases normalized
        (`np.asarray` -> `numpy.asarray`, bare `jit` -> `jax.jit`)."""
        parts = dotted_parts(node)
        if not parts:
            return None
        mapped = self.aliases.get(parts[0])
        if mapped:
            parts = mapped.split(".") + parts[1:]
        return ".".join(parts)

    def iter_scopes(self) -> Iterator[ast.AST]:
        yield self.tree
        for node in ast.walk(self.tree):
            if isinstance(node, SCOPE_TYPES):
                yield node

    def scope_defs(self, scope: ast.AST) -> Dict[str, ast.AST]:
        """Function defs directly visible in `scope` (memoized — the call
        resolvers hit the same scopes once per call site)."""
        cached = self._scope_defs.get(id(scope))
        if cached is None:
            cached = {node.name: node for node in walk_scope(scope)
                      if isinstance(node, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))}
            self._scope_defs[id(scope)] = cached
        return cached

    def self_name(self, scope: ast.AST) -> Optional[Tuple[str, str]]:
        """For a method (or a function nested in one), the instance-arg name
        of the nearest method, plus its class name — (`self`, `Trainer`)."""
        node = scope
        while node is not None and not isinstance(node, ast.Module):
            parent = self.parent(node)
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and isinstance(parent, ast.ClassDef) and node.args.args):
                return node.args.args[0].arg, parent.name  # type: ignore
            node = parent
        return None

    def finding(self, node: ast.AST, rule: str, severity: str,
                message: str) -> Optional[Finding]:
        line = getattr(node, "lineno", 1)
        if rule.upper() in self.file_suppress or "ALL" in self.file_suppress:
            return None
        on_line = self.line_suppress.get(line, set())
        if rule.upper() in on_line or "ALL" in on_line:
            return None
        return Finding(self.path, line, getattr(node, "col_offset", 0) + 1,
                       rule, severity, message)


# -- hot-loop detection (shared by SYNC001 / SHD002) -------------------------
_HOT_CALLEES = re.compile(r"^(train_step|multi_step|train_batch|step_fn)$")
# serving dispatch loops count as hot only for the placement rule (SHD002):
# a batch-detect CLI legitimately fetches outputs per image for host NMS, so
# SYNC001 keeps its train-loop-only scope
_SERVE_CALLEES = re.compile(r"^(predict|submit)$")


def _loop_statements(loop: ast.AST) -> Iterator[ast.AST]:
    """Nodes in the loop's repeated part, not descending into nested defs."""
    for stmt in list(loop.body) + list(getattr(loop, "orelse", [])):
        stack = [stmt]
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, SCOPE_TYPES):
                stack.extend(ast.iter_child_nodes(n))


def _is_hot_loop(loop: ast.AST, config: Config, serve: bool = False) -> bool:
    extra = [re.compile(p) for p in config.hot_loop_callees]
    for n in _loop_statements(loop):
        if isinstance(n, ast.Call):
            name = terminal_name(n.func)
            if not name:
                continue
            bare = name.lstrip("_")
            if _HOT_CALLEES.match(bare) or any(p.search(name) for p in extra):
                return True
            if serve and _SERVE_CALLEES.match(bare):
                return True
    return False


# -- traced-function discovery ----------------------------------------------
# (Moved here from rules.py so the interprocedural reach pass below can seed
# from it without a framework -> rules import cycle.)

JIT_FNS = {"jax.jit", "jax.pjit", "flax.nnx.jit", "nnx.jit"}

TRACE_FNS = JIT_FNS | {
    "jax.grad", "jax.value_and_grad", "jax.jacfwd", "jax.jacrev",
    "jax.vmap", "jax.pmap", "jax.checkpoint", "jax.remat",
    "jax.lax.scan", "jax.lax.map", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.associative_scan",
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.experimental.pallas.pallas_call",
}


def find_local_def(module: Module, call: ast.AST,
                   name: str) -> Optional[ast.AST]:
    """FunctionDef named `name` in the scope chain enclosing `call`."""
    scope = module.enclosing_scope(call)
    while True:
        found = module.scope_defs(scope).get(name)
        if found is not None:
            return found
        if isinstance(scope, ast.Module):
            return None
        scope = module.enclosing_scope(scope)


def traced_functions(module: Module) -> Set[ast.AST]:
    """Function defs (and lambdas) that are traced: passed to a
    jit/grad/vmap/scan/shard_map/pallas_call in this module, or decorated
    with one (incl. `functools.partial(jax.jit, ...)`)."""
    traced: Set[ast.AST] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and module.resolve(node.func) in TRACE_FNS:
            for arg in node.args:
                # `pallas_call(functools.partial(kernel, ...), ...)` — the
                # kernel-binding idiom of ops/attention.py: the partial's
                # target runs under the trace exactly like a bare name
                if (isinstance(arg, ast.Call)
                        and module.resolve(arg.func) == "functools.partial"
                        and arg.args):
                    arg = arg.args[0]
                if isinstance(arg, ast.Lambda):
                    traced.add(arg)
                elif isinstance(arg, ast.Name):
                    fd = find_local_def(module, node, arg.id)
                    if fd is not None:
                        traced.add(fd)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec
                if isinstance(dec, ast.Call):
                    if module.resolve(dec.func) == "functools.partial" \
                            and dec.args:
                        target = dec.args[0]
                    else:
                        target = dec.func
                if module.resolve(target) in TRACE_FNS:
                    traced.add(node)
    return traced


def partial_bound_statics(module: Module) -> Dict[int, Set[str]]:
    """For each directly-traced def (by node id), the parameter names a
    `functools.partial` at the trace call site binds to concrete values —
    trace-time statics, not tracers (the partial's keywords plus the
    leading positionals it fills). Branching on these inside the kernel is
    the normal block-size specialization idiom, so the seed taint in
    `compute_trace_reach` excludes them."""
    statics: Dict[int, Set[str]] = {}
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call)
                and module.resolve(node.func) in TRACE_FNS):
            continue
        for arg in node.args:
            if not (isinstance(arg, ast.Call)
                    and module.resolve(arg.func) == "functools.partial"
                    and arg.args and isinstance(arg.args[0], ast.Name)):
                continue
            fd = find_local_def(module, node, arg.args[0].id)
            fd_args = getattr(fd, "args", None)
            if fd is None or fd_args is None:
                continue
            bound = {kw.arg for kw in arg.keywords if kw.arg}
            pos = fd_args.posonlyargs + fd_args.args
            bound |= {a.arg for a in pos[:len(arg.args) - 1]}
            statics.setdefault(id(fd), set()).update(bound)
    return statics


def traced_closure(module: Module, traced: Set[ast.AST]) -> Set[ast.AST]:
    """Traced defs plus every function nested inside one (their bodies all
    run under the same trace)."""
    out = set(traced)
    for fn in traced:
        for node in ast.walk(fn):
            if isinstance(node, SCOPE_TYPES):
                out.add(node)
    return out


# -- project-wide call graph -------------------------------------------------

class FunctionInfo:
    """One function definition plus where it lives — the call graph's node."""

    __slots__ = ("module", "node", "cls_name", "qualname")

    def __init__(self, module: Module, node: ast.AST,
                 cls_name: Optional[str] = None):
        self.module = module
        self.node = node
        self.cls_name = cls_name
        name = getattr(node, "name", "<lambda>")
        self.qualname = f"{cls_name}.{name}" if cls_name else name

    @property
    def params(self) -> List[str]:
        args = getattr(self.node, "args", None)
        if args is None:
            return []
        out = [a.arg for a in args.posonlyargs + args.args]
        return out

    def param_index(self, skip_self: bool = True) -> List[str]:
        """Positional parameter names as seen by a call site (instance-arg
        dropped for methods called through an instance)."""
        params = self.params
        if skip_self and self.cls_name and params \
                and params[0] in ("self", "cls"):
            return params[1:]
        return params

    def __repr__(self) -> str:  # pragma: no cover
        return f"FunctionInfo({self.module.path}:{self.qualname})"


class CallGraph:
    """Project-wide name resolution for defs and module-level constants.

    Resolution is deliberately name-based (the same terminal-name strategy
    donation.py's factory index proved out): a call site binds to defs it can
    plausibly see — local scope chain first, then same-module defs, then
    cross-module defs *only* when the name was imported. Multiple candidates
    are all returned; analyses union their effects (conservative)."""

    def __init__(self, modules: Iterable[Module]):
        self.modules = list(modules)
        # terminal def name -> every project def with that name
        self.defs: Dict[str, List[FunctionInfo]] = {}
        # class name -> method name -> FunctionInfo
        self.methods: Dict[str, Dict[str, FunctionInfo]] = {}
        # terminal constant name -> string/tuple-of-string values assigned at
        # module level anywhere in the project (mesh axis names and friends)
        self.constants: Dict[str, List[object]] = {}
        self.info_of: Dict[int, FunctionInfo] = {}
        self._resolve_cache: Dict[int, List[FunctionInfo]] = {}
        for module in self.modules:
            self._index_module(module)

    def _index_module(self, module: Module) -> None:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                parent = module.parent(node)
                cls = parent.name if isinstance(parent, ast.ClassDef) else None
                info = FunctionInfo(module, node, cls)
                self.defs.setdefault(node.name, []).append(info)
                if cls:
                    self.methods.setdefault(cls, {})[node.name] = info
                self.info_of[id(node)] = info
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                val = _const_value(node.value)
                if val is not None:
                    self.constants.setdefault(
                        node.targets[0].id, []).append(val)

    def info(self, node: ast.AST) -> Optional[FunctionInfo]:
        return self.info_of.get(id(node))

    def resolve_call(self, module: Module,
                     call: ast.Call) -> List[FunctionInfo]:
        """Project defs a call site may invoke ([] when the callee is not a
        plain def reference we can see — jitted objects, params, builtins).
        Memoized per call node — the fixpoints revisit call sites."""
        cached = self._resolve_cache.get(id(call))
        if cached is None:
            cached = self._resolve_call(module, call)
            self._resolve_cache[id(call)] = cached
        return cached

    def _resolve_call(self, module: Module,
                      call: ast.Call) -> List[FunctionInfo]:
        func = call.func
        if isinstance(func, ast.Name):
            local = find_local_def(module, call, func.id)
            if local is not None:
                info = self.info(local)
                return [info] if info else []
            if func.id in module.aliases:  # imported name
                target = module.aliases[func.id].rsplit(".", 1)[-1]
                return [i for i in self.defs.get(target, [])
                        if i.cls_name is None]
            return []
        if isinstance(func, ast.Attribute):
            # self.method(...) within a class body
            ctx = module.self_name(module.enclosing_scope(call))
            if ctx and isinstance(func.value, ast.Name) \
                    and func.value.id == ctx[0]:
                info = self.methods.get(ctx[1], {}).get(func.attr)
                return [info] if info else []
            # mod.fn(...) through an imported module
            parts = dotted_parts(func)
            if parts and parts[0] in module.import_roots:
                return [i for i in self.defs.get(func.attr, [])
                        if i.cls_name is None]
        return []

    def resolve_strings(self, module: Module, node: ast.AST,
                        scope: Optional[ast.AST] = None,
                        _depth: int = 0) -> List[str]:
        """Every string a simple expression can evaluate to: constants,
        tuples/lists/sets of them, `a or b` fallbacks, module-level constant
        names (local module first, then project-wide by terminal name), and
        — when `scope` is given — names assigned within that scope
        (`names = axis_names or (DATA_AXIS, MODEL_AXIS)` then
        `Mesh(grid, names)`, the parallel/mesh.py idiom). Returns [] when
        nothing is statically resolvable."""
        if _depth > 6:
            return []
        if isinstance(node, ast.Constant):
            return [node.value] if isinstance(node.value, str) else []
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out: List[str] = []
            for el in node.elts:
                out.extend(self.resolve_strings(module, el, scope, _depth + 1))
            return out
        if isinstance(node, ast.BoolOp):
            out = []
            for v in node.values:
                out.extend(self.resolve_strings(module, v, scope, _depth + 1))
            return out
        if isinstance(node, ast.IfExp):
            return (self.resolve_strings(module, node.body, scope, _depth + 1)
                    + self.resolve_strings(module, node.orelse, scope,
                                           _depth + 1))
        if isinstance(node, ast.Name):
            if scope is not None:
                local: List[str] = []
                for n in walk_scope(scope):
                    if isinstance(n, ast.Assign) and n.value is not node \
                            and any(isinstance(t, ast.Name)
                                    and t.id == node.id for t in n.targets):
                        local.extend(self.resolve_strings(
                            module, n.value, scope, _depth + 1))
                if local:
                    return local
            vals = self.constants.get(node.id, [])
            return [s for v in vals for s in _strings_of(v)]
        if isinstance(node, ast.Attribute):
            vals = self.constants.get(node.attr, [])
            return [s for v in vals for s in _strings_of(v)]
        return []


def _const_value(node: ast.AST):
    """Literal value of a module-level constant assignment we care about:
    a string, or a tuple/list of strings. None otherwise."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _strings_of(value) -> List[str]:
    if isinstance(value, str):
        return [value]
    if isinstance(value, tuple):
        return [v for v in value if isinstance(v, str)]
    return []


# -- tracer-use classification (shared by TRC001 and the reach pass) ---------

SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
              "is_fully_replicated"}
SAFE_CALLS = {"isinstance", "len", "hasattr", "type", "callable", "id",
              "getattr", "repr", "str"}


def unsafe_tracer_use(module: Module, name: ast.AST, root: ast.AST) -> bool:
    """Climb from a tainted Name toward `root`: uses that stay static at
    trace time (shape/dtype inspection, isinstance, `is None`) are safe;
    anything that produces a value dependent on the tracer's DATA is not."""
    cur = name
    while cur is not root:
        parent = module.parent(cur)
        if parent is None:
            break
        if isinstance(parent, ast.Attribute) and parent.value is cur \
                and parent.attr in SAFE_ATTRS:
            return False
        if isinstance(parent, ast.Call):
            in_args = cur in parent.args or any(
                kw.value is cur for kw in parent.keywords)
            if in_args:
                fn = terminal_name(parent.func)
                return fn not in SAFE_CALLS
            if cur is parent.func:
                return True  # calling a tracer-valued thing -> tracer result
        if isinstance(parent, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops):
            return False
        cur = parent
    return True


# -- interprocedural trace reach + argument taint ----------------------------

class ReachedFn:
    """One function known to execute under a jax trace.

    `tainted` holds the parameter names that can carry tracer values: every
    parameter for trace entry points (seeds — jit/grad/vmap/... see the
    actual call), and for functions only *called* from traced code, exactly
    the parameters some traced call site passes a tainted value to. That
    per-call-site mapping is what keeps interprocedural TRC001 from flagging
    host-side config flags threaded into shared helpers."""

    __slots__ = ("info", "tainted", "seed")

    def __init__(self, info: FunctionInfo, tainted: Set[str], seed: bool):
        self.info = info
        self.tainted = tainted
        self.seed = seed


def _map_call_args(call: ast.Call, callee: FunctionInfo,
                   skip_self: bool) -> Iterator[Tuple[ast.AST, str]]:
    """(argument expression, parameter name) pairs for a call site. Stops
    positional mapping at a * unpacking."""
    params = callee.param_index(skip_self=skip_self)
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(params):
            yield arg, params[i]
    all_params = set(callee.params)
    for kw in call.keywords:
        if kw.arg and kw.arg in all_params:
            yield kw.value, kw.arg


def _expr_carries_taint(module: Module, expr: ast.AST,
                        tainted: Set[str]) -> bool:
    """A call argument propagates taint only when a tainted name reaches it
    through a value-producing use — `x.shape[1]` / `isinstance(x, ...)` are
    trace-time statics and stay clean (same policy as TRC001)."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in tainted \
                and isinstance(n.ctx, ast.Load) \
                and unsafe_tracer_use(module, n, expr):
            return True
    return False


def compute_trace_reach(graph: CallGraph) -> Dict[int, ReachedFn]:
    """Fixpoint over the call graph: which functions run under a trace, and
    which of their parameters may be tracers.

    Seeds are each module's directly-traced defs (plus nested defs — one
    trace closure), with every parameter tainted. A call from reached code
    to a project def marks the callee reached and taints the callee params
    receiving expressions that mention a tainted name of the caller."""
    reach: Dict[int, ReachedFn] = {}
    work: List[FunctionInfo] = []

    def add(info: FunctionInfo, tainted: Set[str], seed: bool) -> None:
        cur = reach.get(id(info.node))
        if cur is None:
            reach[id(info.node)] = ReachedFn(info, set(tainted), seed)
            work.append(info)
        elif not tainted <= cur.tainted or (seed and not cur.seed):
            cur.tainted |= tainted
            cur.seed = cur.seed or seed
            work.append(info)

    for module in graph.modules:
        statics = partial_bound_statics(module)
        for fn in traced_closure(module, traced_functions(module)):
            info = graph.info(fn)
            if info is None:  # lambdas: no params worth tracking, no calls
                info = FunctionInfo(module, fn)
                graph.info_of[id(fn)] = info
            params = set(info.params) - {"self", "cls"}
            args = getattr(fn, "args", None)
            if args is not None:
                if args.vararg:
                    params.add(args.vararg.arg)
                params |= {a.arg for a in args.kwonlyargs}
            add(info, params - statics.get(id(fn), set()), seed=True)

    while work:
        caller = work.pop()
        entry = reach[id(caller.node)]
        for node in walk_scope(caller.node):
            if not isinstance(node, ast.Call):
                continue
            skip_self = isinstance(node.func, ast.Attribute)
            for callee in graph.resolve_call(caller.module, node):
                tainted = {param for arg, param
                           in _map_call_args(node, callee, skip_self)
                           if _expr_carries_taint(caller.module, arg,
                                                  entry.tainted)}
                add(callee, tainted, seed=False)
                # the callee's nested defs share its trace
                for sub in ast.walk(callee.node):
                    if sub is not callee.node and isinstance(sub, SCOPE_TYPES):
                        sub_info = graph.info(sub)
                        if sub_info is None:
                            sub_info = FunctionInfo(callee.module, sub,
                                                    callee.cls_name)
                            graph.info_of[id(sub)] = sub_info
                        add(sub_info, set(), seed=False)
    return reach
