"""jaxlint plumbing: findings, suppressions, config, and AST utilities.

Everything here is stdlib-only — the linter must run (and run fast) on hosts
with no jax installed, and importing jax would drag backend init into what is
a pure source-level pass.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

# scopes that cut off name visibility / execution locality for our analyses
SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    severity: str
    message: str

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# -- inline suppression ------------------------------------------------------
# `# jaxlint: disable=DON001[,SYNC001]` on the flagged line suppresses those
# rules there; `# jaxlint: disable-file=RULE` anywhere suppresses file-wide.
_DIRECTIVE_RE = re.compile(
    r"#\s*jaxlint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_*,\s]+)")


def parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for lineno, line in enumerate(source.splitlines(), 1):
        m = _DIRECTIVE_RE.search(line)
        if not m:
            continue
        rules = {r.strip().upper() for r in m.group(2).split(",") if r.strip()}
        if m.group(1) == "disable-file":
            file_wide |= rules
        else:
            per_line.setdefault(lineno, set()).update(rules)
            if line.lstrip().startswith("#"):
                # a comment-only directive line also covers the next line,
                # so suppressions fit an 79-col style
                per_line.setdefault(lineno + 1, set()).update(rules)
    return per_line, file_wide


# -- config ------------------------------------------------------------------
@dataclasses.dataclass
class Config:
    """`[tool.jaxlint]` in pyproject.toml. All keys optional."""
    exclude: Tuple[str, ...] = ()          # path globs / directory prefixes
    disable: Tuple[str, ...] = ()          # rule ids disabled project-wide
    hot_loop_callees: Tuple[str, ...] = () # extra callee names marking a loop hot
    sync_allowed_guards: Tuple[str, ...] = ()  # extra guard-name patterns

    def rule_enabled(self, rule_id: str) -> bool:
        return rule_id.upper() not in {r.upper() for r in self.disable}

    def is_excluded(self, path: str, root: str) -> bool:
        rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
        for pat in self.exclude:
            pat = pat.rstrip("/")
            if (fnmatch.fnmatch(rel, pat) or fnmatch.fnmatch(rel, pat + "/*")
                    or rel == pat or rel.startswith(pat + "/")):
                return True
        return False


def _split_inline_comment(line: str) -> str:
    """Drop a trailing `# ...` comment, respecting simple quoted strings."""
    quote = None
    for i, ch in enumerate(line):
        if quote:
            if ch == quote:
                quote = None
        elif ch in ("'", '"'):
            quote = ch
        elif ch == "#":
            return line[:i]
    return line


def _parse_toml_value(text: str):
    text = text.strip()
    if text.startswith("[") and text.endswith("]"):
        body = text[1:-1]
        items, cur, quote = [], "", None
        for ch in body:
            if quote:
                cur += ch
                if ch == quote:
                    quote = None
            elif ch in ("'", '"'):
                quote = ch
                cur += ch
            elif ch == ",":
                items.append(cur)
                cur = ""
            else:
                cur += ch
        if cur.strip():
            items.append(cur)
        return [_parse_toml_value(i) for i in items if i.strip()]
    if (text.startswith('"') and text.endswith('"')) or (
            text.startswith("'") and text.endswith("'")):
        return text[1:-1]
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        return text


def parse_tool_section(source: str, section: str = "tool.jaxlint") -> dict:
    """Minimal TOML-subset reader for one `[section]` of pyproject.toml.

    Python 3.10 has no stdlib tomllib and jaxlint adds no dependencies, so
    this handles exactly what the section needs: string / bool / int values
    and (possibly multi-line) arrays of strings. Unknown shapes are ignored.
    """
    out: dict = {}
    in_section = False
    pending_key: Optional[str] = None
    pending_val = ""
    for raw in source.splitlines():
        line = _split_inline_comment(raw).rstrip()
        stripped = line.strip()
        if pending_key is not None:
            pending_val += " " + stripped
            if pending_val.count("[") <= pending_val.count("]"):
                out[pending_key] = _parse_toml_value(pending_val)
                pending_key, pending_val = None, ""
            continue
        if stripped.startswith("["):
            in_section = stripped == f"[{section}]"
            continue
        if not in_section or "=" not in stripped:
            continue
        key, _, val = stripped.partition("=")
        key, val = key.strip().strip('"').strip("'"), val.strip()
        if val.count("[") > val.count("]"):
            pending_key, pending_val = key, val
            continue
        out[key] = _parse_toml_value(val)
    return out


def find_pyproject(start: str) -> Optional[str]:
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        cand = os.path.join(cur, "pyproject.toml")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def load_config(pyproject_path: Optional[str]) -> Config:
    if not pyproject_path or not os.path.isfile(pyproject_path):
        return Config()
    with open(pyproject_path, encoding="utf-8") as fp:
        raw = parse_tool_section(fp.read())

    def strings(key) -> Tuple[str, ...]:
        val = raw.get(key, [])
        if isinstance(val, str):
            val = [val]
        return tuple(str(v) for v in val if isinstance(v, (str, int)))

    return Config(exclude=strings("exclude"),
                  disable=strings("disable"),
                  hot_loop_callees=strings("hot-loop-callees"),
                  sync_allowed_guards=strings("sync-allowed-guards"))


# -- AST module context ------------------------------------------------------
def dotted_parts(node: ast.AST) -> Optional[List[str]]:
    """`a.b.c` -> ["a", "b", "c"]; None for anything not a pure name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def dotted_str(node: ast.AST) -> Optional[str]:
    parts = dotted_parts(node)
    return ".".join(parts) if parts else None


def terminal_name(node: ast.AST) -> Optional[str]:
    """Last segment of a callee: `steps.make_yolo_train_step` -> the latter."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def walk_scope(scope: ast.AST) -> Iterator[ast.AST]:
    """Yield nodes belonging to `scope`, NOT descending into nested function
    scopes (the nested defs themselves are yielded, their bodies are not).
    Comprehensions are treated as part of the enclosing scope."""
    stack = list(ast.iter_child_nodes(scope))[::-1]
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, SCOPE_TYPES):
            stack.extend(list(ast.iter_child_nodes(node))[::-1])


class Module:
    """One parsed file plus the cross-referencing helpers rules need."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[id(child)] = parent
        self.aliases, self.import_roots = self._collect_aliases()
        self.line_suppress, self.file_suppress = parse_suppressions(source)

    @classmethod
    def from_path(cls, path: str) -> "Module":
        with open(path, encoding="utf-8") as fp:
            return cls(path, fp.read())

    def _collect_aliases(self) -> Tuple[Dict[str, str], Set[str]]:
        aliases: Dict[str, str] = {}
        roots: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    aliases[local] = a.name if a.asname else a.name.split(".")[0]
                    roots.add(local)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    aliases[local] = f"{node.module}.{a.name}"
                    roots.add(local)
        return aliases, roots

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_scope(self, node: ast.AST) -> ast.AST:
        for anc in self.ancestors(node):
            if isinstance(anc, SCOPE_TYPES) or isinstance(anc, ast.Module):
                return anc
        return self.tree

    def statement_of(self, node: ast.AST) -> ast.stmt:
        cur = node
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.Module, *SCOPE_TYPES)) or isinstance(
                    anc, (ast.If, ast.For, ast.While, ast.With, ast.Try)):
                if isinstance(cur, ast.stmt):
                    return cur
            cur = anc
        return cur if isinstance(cur, ast.stmt) else node  # pragma: no cover

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Callee dotted path with import aliases normalized
        (`np.asarray` -> `numpy.asarray`, bare `jit` -> `jax.jit`)."""
        parts = dotted_parts(node)
        if not parts:
            return None
        mapped = self.aliases.get(parts[0])
        if mapped:
            parts = mapped.split(".") + parts[1:]
        return ".".join(parts)

    def iter_scopes(self) -> Iterator[ast.AST]:
        yield self.tree
        for node in ast.walk(self.tree):
            if isinstance(node, SCOPE_TYPES):
                yield node

    def self_name(self, scope: ast.AST) -> Optional[Tuple[str, str]]:
        """For a method (or a function nested in one), the instance-arg name
        of the nearest method, plus its class name — (`self`, `Trainer`)."""
        node = scope
        while node is not None and not isinstance(node, ast.Module):
            parent = self.parent(node)
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and isinstance(parent, ast.ClassDef) and node.args.args):
                return node.args.args[0].arg, parent.name  # type: ignore
            node = parent
        return None

    def finding(self, node: ast.AST, rule: str, severity: str,
                message: str) -> Optional[Finding]:
        line = getattr(node, "lineno", 1)
        if rule.upper() in self.file_suppress or "ALL" in self.file_suppress:
            return None
        on_line = self.line_suppress.get(line, set())
        if rule.upper() in on_line or "ALL" in on_line:
            return None
        return Finding(self.path, line, getattr(node, "col_offset", 0) + 1,
                       rule, severity, message)
