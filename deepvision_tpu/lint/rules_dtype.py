"""Dtype-policy rules: full-precision leaks in a declared bf16 compute path.

The r05 ResNet-50 traffic grid showed the bf16 compute policy is THE
HBM-bandwidth lever (97.4% of roof, runs/r05_resnet50_tpu_profile): an f32
tensor on the hot path doubles every read and write it touches, produces
numerically-correct results, and therefore survives every test. Two
mechanically-detectable shapes of that leak:

  DTY001  a value explicitly materialized in float32/float64 inside traced
          code is fed to the model's apply fn uncast — the whole forward
          (and its backward) runs full-precision under a declared bf16
          policy. Return dtypes propagate through the project call graph,
          so a helper that forgot its `.astype(compute_dtype)` is caught at
          the call site.
  DTY002  a host-side upcast at a jit dispatch boundary
          (`step(x.astype(np.float32))`, `device_put(np.asarray(x,
          np.float32))`): the cast belongs INSIDE the jitted program —
          staging f32 ships 4x the bytes of the uint8 pixels
          (docs/INPUT_PIPELINE.md; bench_input.py measured 3.07x
          end-to-end).

DTY001 only runs when pyproject declares the policy
(`[tool.jaxlint] compute-dtype = "bfloat16"`); with an f32 policy there is
nothing to leak. DTY002 is about transfer bytes, not compute dtype, and is
always on.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .donation import ProjectIndex
from .framework import (Config, Finding, Module, SEVERITY_WARNING, dotted_str,
                        walk_scope)

_FULL_PRECISION = {
    "jax.numpy.float32", "jax.numpy.float64", "numpy.float32",
    "numpy.float64",
}
_FULL_PRECISION_STR = {"float32", "float64", "f32", "f64"}

# array-creating callables where an explicit dtype kwarg pins the result
_CREATORS = re.compile(
    r"^(jax\.numpy|numpy)\.(asarray|array|zeros|ones|full|empty|arange|"
    r"linspace|eye|zeros_like|ones_like|full_like)$")

_APPLY_RE = re.compile(r"(^|_)apply(_fn)?$")


def _is_full_precision_dtype(module: Module, node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return node.value in _FULL_PRECISION_STR
    resolved = module.resolve(node)
    return resolved in _FULL_PRECISION if resolved else False


def _explicit_dtype(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    return None


def _value_kind(module: Module, node: ast.AST,
                returns_f32: Set[int],
                index: ProjectIndex) -> Optional[str]:
    """'f32' when the expression materializes a full-precision array,
    'cast' when it explicitly casts to something else (kills taint),
    None when we can't tell."""
    if not isinstance(node, ast.Call):
        return None
    # <x>.astype(dtype)
    if isinstance(node.func, ast.Attribute) and node.func.attr == "astype" \
            and len(node.args) == 1 and not node.keywords:
        return "f32" if _is_full_precision_dtype(module, node.args[0]) \
            else "cast"
    resolved = module.resolve(node.func)
    if resolved and _CREATORS.match(resolved):
        dtype = _explicit_dtype(node)
        if dtype is not None:
            return "f32" if _is_full_precision_dtype(module, dtype) \
                else "cast"
        if len(node.args) >= 2 \
                and resolved.rsplit(".", 1)[-1] in ("asarray", "array"):
            return "f32" if _is_full_precision_dtype(module, node.args[1]) \
                else None
        return None
    dtype = _explicit_dtype(node)
    if dtype is not None and _is_full_precision_dtype(module, dtype):
        return "f32"
    if index.graph is not None:
        for callee in index.graph.resolve_call(module, node):
            if id(callee.node) in returns_f32:
                return "f32"
    return None


def _returns_f32(index: ProjectIndex) -> Set[int]:
    """id(def node) for project functions whose return value is an
    explicitly full-precision array — fixpoint so a wrapper returning a
    full-precision helper's result is marked too."""
    cached = index.cache.get("dty_returns_f32")
    if cached is not None:
        return cached
    marked: Set[int] = set()
    graph = index.graph
    infos = [] if graph is None else [i for lst in graph.defs.values()
                                      for i in lst]
    changed = True
    while changed:
        changed = False
        for info in infos:
            if id(info.node) in marked:
                continue
            for node in walk_scope(info.node):
                if isinstance(node, ast.Return) and node.value is not None \
                        and _value_kind(info.module, node.value, marked,
                                        index) == "f32":
                    marked.add(id(info.node))
                    changed = True
                    break
    index.cache["dty_returns_f32"] = marked
    return marked


def check_dty001(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    policy = config.compute_dtype.lower()
    if policy not in ("bfloat16", "float16", "bf16", "f16"):
        return []
    returns_f32 = _returns_f32(index)
    findings: List[Finding] = []
    seen: Set[int] = set()
    for entry in index.reached_in(module):
        fn = entry.info.node
        if id(fn) in seen or isinstance(fn, ast.Lambda):
            continue
        seen.add(id(fn))
        # linear scan in source order: assignments taint/untaint names,
        # apply-fn calls are the sinks
        events: List[Tuple[Tuple[int, int], str, ast.AST]] = []
        for node in walk_scope(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                events.append(((node.lineno, node.col_offset), "assign",
                               node))
            elif isinstance(node, ast.Call):
                name = dotted_str(node.func)
                tail = name.rsplit(".", 1)[-1] if name else None
                if tail and _APPLY_RE.search(tail):
                    events.append(((node.lineno, node.col_offset), "sink",
                                   node))
        events.sort(key=lambda e: e[0])
        tainted: Dict[str, int] = {}  # name -> taint-site line
        for _, kind, node in events:
            if kind == "assign":
                tgt = node.targets[0].id
                vk = _value_kind(module, node.value, returns_f32, index)
                if vk == "f32":
                    tainted[tgt] = node.lineno
                elif tgt in tainted:
                    del tainted[tgt]
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in tainted:
                    f = module.finding(
                        node, "DTY001", SEVERITY_WARNING,
                        f"'{arg.id}' was materialized in full precision "
                        f"(line {tainted[arg.id]}) and reaches the model's "
                        f"apply fn uncast under the declared "
                        f"'{config.compute_dtype}' compute policy — the "
                        f"whole forward/backward runs f32 and doubles HBM "
                        f"traffic; cast first "
                        f"(`{arg.id} = {arg.id}.astype(compute_dtype)`, "
                        f"core/steps.py:_normalize_input)")
                    if f:
                        findings.append(f)
    return findings


# ---------------------------------------------------------------------------
# DTY002 — host upcast at a jit boundary
# ---------------------------------------------------------------------------

def _host_upcast(module: Module, expr: ast.AST) -> Optional[str]:
    """Describe `expr` when it is an explicit full-precision cast performed
    on the host side of a dispatch ('x.astype(np.float32)' etc.)."""
    if not isinstance(expr, ast.Call):
        return None
    if isinstance(expr.func, ast.Attribute) and expr.func.attr == "astype" \
            and len(expr.args) == 1 \
            and _is_full_precision_dtype(module, expr.args[0]):
        return ".astype(float32)"
    resolved = module.resolve(expr.func)
    if resolved and _CREATORS.match(resolved):
        dtype = _explicit_dtype(expr)
        if dtype is None and len(expr.args) >= 2 \
                and resolved.rsplit(".", 1)[-1] in ("asarray", "array"):
            dtype = expr.args[1]
        if dtype is not None and _is_full_precision_dtype(module, dtype):
            return f"{resolved.rsplit('.', 1)[-1]}(..., float32)"
    return None


def check_dty002(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    findings: List[Finding] = []
    for scope in module.iter_scopes():
        jitted = index.jitted.callable_spellings(module, scope)
        for node in walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_str(node.func)
            resolved = module.resolve(node.func)
            if callee in jitted:
                args = list(node.args) + [kw.value for kw in node.keywords]
                boundary = f"jitted callable '{callee}'"
            elif resolved == "jax.device_put" and node.args:
                args = [node.args[0]]
                boundary = "jax.device_put"
            else:
                continue
            for arg in args:
                what = _host_upcast(module, arg)
                if not what:
                    continue
                f = module.finding(
                    arg, "DTY002", SEVERITY_WARNING,
                    f"host-side {what} at the {boundary} boundary: the "
                    f"upcast runs on host and ships 4x the bytes of the "
                    f"raw uint8 pixels over PCIe/ICI every dispatch — move "
                    f"the cast inside the jitted function (input_norm / "
                    f"device_augment stage batches as uint8 and convert "
                    f"on device, docs/INPUT_PIPELINE.md)")
                if f:
                    findings.append(f)
    return findings
