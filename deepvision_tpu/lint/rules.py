"""The jaxlint rules.

Each rule is a function `(module, index, config) -> [Finding]`, registered in
ALL_RULES. The rules are deliberately heuristic: they trade exhaustive
soundness for zero-dependency, sub-second analysis that catches the hazard
classes this codebase has actually been bitten by (see docs/LINTING.md for
the per-rule rationale and the TPU cost of each hazard).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .donation import JIT_FNS, Donation, ProjectIndex, _dict_donations
from .framework import (Config, Finding, Module, SCOPE_TYPES, SEVERITY_ERROR,
                        SEVERITY_WARNING, TRACE_FNS, _is_hot_loop,
                        _loop_statements, dotted_str, find_local_def,
                        terminal_name, traced_closure, traced_functions,
                        walk_scope)

Pos = Tuple[int, int]


def _pos(node: ast.AST) -> Pos:
    return (node.lineno, node.col_offset)


def _end(node: ast.AST) -> Pos:
    return (getattr(node, "end_lineno", node.lineno),
            getattr(node, "end_col_offset", node.col_offset))


def _span_contains(outer: ast.AST, pos: Pos) -> bool:
    return _pos(outer) <= pos <= _end(outer)


# ---------------------------------------------------------------------------
# DON001 — use-after-donate
# ---------------------------------------------------------------------------

def _assigned_names(target: ast.AST) -> Iterator[str]:
    """Dotted names stored by an assignment target (tuples unpacked)."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            yield from _assigned_names(el)
    elif isinstance(target, ast.Starred):
        yield from _assigned_names(target.value)
    else:
        name = dotted_str(target)
        if name:
            yield name


def _name_events(scope: ast.AST, module: Module,
                 target: str) -> List[Tuple[Pos, str]]:
    """(position, 'load'|'store') events for dotted name `target` in scope.
    An AugAssign target is both: it reads the old buffer before storing."""
    events: List[Tuple[Pos, str]] = []
    for node in walk_scope(scope):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if dotted_str(node) != target:
                continue
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, ast.Load):
                events.append((_pos(node), "load"))
            elif isinstance(ctx, (ast.Store, ast.Del)):
                parent = module.parent(node)
                if isinstance(parent, ast.AugAssign) and parent.target is node:
                    events.append((_pos(node), "load"))
                events.append((_pos(node), "store"))
    events.sort()
    return events


def _gather_donating_callables(scope: ast.AST, module: Module,
                               index: ProjectIndex) -> Dict[str, Donation]:
    """Callables reachable in `scope` whose donation we know, keyed by the
    exact call spelling (`step`, `self.train_step`, ...)."""
    donating: Dict[str, Donation] = {}
    # module-level donating names are visible inside functions
    donating.update(index.module_names.get(module.path, {}))

    ctx = module.self_name(scope)
    cls_name = self_arg = None
    if ctx:
        self_arg, cls_name = ctx
        for attr, don in index.class_attrs.get(cls_name, {}).items():
            donating[f"{self_arg}.{attr}"] = don

    dicts = _dict_donations(scope)
    local_factories: Dict[str, Donation] = {}
    for node in walk_scope(scope):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        tgt = node.targets[0].id
        lam = index._lambda_factory_donation(node.value, module)
        if lam:
            local_factories[tgt] = lam
            continue
        don = index.value_donation(node.value, module, dicts, local_factories,
                                   cls_name, self_arg)
        if don:
            donating[tgt] = don
        elif tgt in donating:
            del donating[tgt]  # rebound to something unknown — stop tracking
    return donating


def _donated_arg_names(call: ast.Call, don: Donation) -> List[ast.AST]:
    """The argument expressions donated at this call site, restricted to
    plain dotted names we can track. A * unpacking shifts positions — bail
    on positional donation past it."""
    out: List[ast.AST] = []
    star_at = next((i for i, a in enumerate(call.args)
                    if isinstance(a, ast.Starred)), None)
    for i in don.argnums:
        if star_at is not None and i >= star_at:
            break
        if i < len(call.args) and dotted_str(call.args[i]):
            out.append(call.args[i])
    for name in don.argnames:
        for kw in call.keywords:
            if kw.arg == name and dotted_str(kw.value):
                out.append(kw.value)
    return out


def check_don001(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    findings: List[Finding] = []
    for scope in module.iter_scopes():
        donating = _gather_donating_callables(scope, module, index)
        if not donating:
            continue
        for node in walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            key = dotted_str(node.func)
            don = donating.get(key) if key else None
            if not don:
                continue
            for arg in _donated_arg_names(node, don):
                f = _use_after_donate(scope, module, node, arg, key)
                if f:
                    findings.append(f)
    return findings


def _use_after_donate(scope: ast.AST, module: Module, call: ast.Call,
                      arg: ast.AST, callee: str) -> Optional[Finding]:
    target = dotted_str(arg)
    events = _name_events(scope, module, target)
    call_start, call_end = _pos(call), _end(call)

    # the statement holding the call may itself rebind the donated name
    # (`state, m = step(state, ...)`) — that store lands right after the call
    stmt = module.statement_of(call)
    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        for t in targets:
            if target in _assigned_names(t):
                # just past the call's end, so the straight-line scan below
                # sees the rebind before any later load
                events.append(((call_end[0], call_end[1] + 1), "store"))
    events.sort()

    def report(load_pos: Pos) -> Optional[Finding]:
        return module.finding(
            _FakeNode(load_pos), "DON001", SEVERITY_ERROR,
            f"'{target}' is read after being donated to '{callee}' — "
            f"donation invalidates the argument's buffers (donate_argnums), "
            f"so this read sees freed memory; rebind '{target}' to the "
            f"result first (e.g. `{target} = {callee}({target}, ...)`) or "
            f"drop the donation")

    # straight-line: first load after the call with no intervening store
    for pos, kind in events:
        if pos <= call_end:
            continue
        if kind == "store":
            break
        return report(pos)

    # loop wraparound: a load earlier in the enclosing loop body re-runs
    # after the donating call on the next iteration; only a store somewhere
    # in the loop makes that safe
    loop = None
    for anc in module.ancestors(call):
        if isinstance(anc, (ast.For, ast.While)):
            loop = anc
            break
        if isinstance(anc, SCOPE_TYPES):
            break
    if loop is not None:
        loop_events = [(p, k) for p, k in events
                       if _span_contains(loop, p)]
        if not any(k == "store" for _, k in loop_events):
            for pos, kind in loop_events:
                if kind == "load" and pos < call_start \
                        and not _span_contains(call, pos):
                    return report(pos)
    return None


class _FakeNode:
    """Position carrier for findings reported at a (line, col) rather than a
    live AST node."""

    def __init__(self, pos: Pos):
        self.lineno, self.col_offset = pos


# ---------------------------------------------------------------------------
# JIT001 — jit built per-iteration / per-call
# ---------------------------------------------------------------------------

def check_jit001(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        parent = module.parent(node)
        immediately_invoked = isinstance(parent, ast.Call) \
            and parent.func is node
        if module.resolve(node.func) in JIT_FNS and not immediately_invoked:
            loop = _repeating_loop(module, node)
            if loop is not None:
                f = module.finding(
                    node, "JIT001", SEVERITY_ERROR,
                    "jax.jit called inside a loop: every iteration builds a "
                    "fresh jitted callable and retraces/recompiles — hoist "
                    "the jit to setup time (factory pattern, e.g. "
                    "core/train_state.py:make_ema_update) and call the "
                    "compiled function in the loop")
                if f:
                    findings.append(f)
        if isinstance(node.func, ast.Call) \
                and module.resolve(node.func.func) in JIT_FNS:
            f = module.finding(
                node, "JIT001", SEVERITY_ERROR,
                "jit-and-call in one expression (`jax.jit(f)(...)`): the "
                "jitted callable is discarded after the call, so every "
                "invocation retraces — bind `jitted = jax.jit(f)` once and "
                "reuse it")
            if f:
                findings.append(f)
    return findings


def _repeating_loop(module: Module, node: ast.AST) -> Optional[ast.AST]:
    """Nearest For/While whose *repeated* part contains `node`, with no
    function boundary in between (a def inside a loop only traces when
    called — the immediate-invocation arm covers that)."""
    cur = node
    for anc in module.ancestors(node):
        if isinstance(anc, SCOPE_TYPES):
            return None
        if isinstance(anc, ast.For) and cur is not anc.iter:
            return anc  # body/orelse/target re-run; iter evaluates once
        if isinstance(anc, ast.While):
            return anc  # test AND body re-run every iteration
        cur = anc
    return None


# ---------------------------------------------------------------------------
# SYNC001 — host synchronization inside a hot training loop
# ---------------------------------------------------------------------------

_SYNC_PATHS = {"jax.device_get"}
_SYNC_NP = {"numpy.asarray", "numpy.array"}
_GUARD_NAMES = re.compile(r"log|flush|every|interval|debug|verbose",
                          re.IGNORECASE)


def _sync_call_kind(node: ast.Call, module: Module) -> Optional[str]:
    if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
            and not node.args and not node.keywords:
        return ".item()"
    resolved = module.resolve(node.func)
    if resolved in _SYNC_PATHS:
        return resolved
    if resolved in _SYNC_NP:
        return resolved.replace("numpy.", "np.")
    if isinstance(node.func, ast.Name) and node.func.id == "float" \
            and len(node.args) == 1 \
            and not isinstance(node.args[0], ast.Constant):
        return "float()"
    return None


def _in_flush_guard(module: Module, node: ast.AST, loop: ast.AST,
                    config: Config) -> bool:
    """True when an ancestor `if` between node and the loop looks like a
    periodic/metrics-flush gate: a modulo or floor-division in the test, or
    a guard name like log_every."""
    extra = [re.compile(p) for p in config.sync_allowed_guards]
    for anc in module.ancestors(node):
        if anc is loop:
            break
        if isinstance(anc, ast.If):
            for sub in ast.walk(anc.test):
                if isinstance(sub, ast.BinOp) and isinstance(
                        sub.op, (ast.Mod, ast.FloorDiv)):
                    return True
                if isinstance(sub, ast.Name):
                    if _GUARD_NAMES.search(sub.id) or any(
                            p.search(sub.id) for p in extra):
                        return True
                if isinstance(sub, ast.Attribute):
                    if _GUARD_NAMES.search(sub.attr) or any(
                            p.search(sub.attr) for p in extra):
                        return True
    return False


def check_sync001(module: Module, index: ProjectIndex,
                  config: Config) -> List[Finding]:
    findings: List[Finding] = []
    for loop in ast.walk(module.tree):
        if not isinstance(loop, (ast.For, ast.While)):
            continue
        # only the OUTERMOST hot loop reports, so nested loops don't double up
        if any(isinstance(a, (ast.For, ast.While)) and _is_hot_loop(a, config)
               for a in module.ancestors(loop)):
            continue
        if not _is_hot_loop(loop, config):
            continue
        for node in _loop_statements(loop):
            if not isinstance(node, ast.Call):
                continue
            kind = _sync_call_kind(node, module)
            if not kind:
                continue
            if _in_flush_guard(module, node, loop, config):
                continue
            f = module.finding(
                node, "SYNC001", SEVERITY_WARNING,
                f"{kind} inside a training loop blocks the host on the "
                f"device every step, serializing dispatch with compute — "
                f"keep metrics as device arrays and fetch them at epoch end "
                f"or under a periodic `step % log_every` guard "
                f"(core/trainer.py:train_epoch is the pattern)")
            if f:
                findings.append(f)
    return findings


# ---------------------------------------------------------------------------
# traced-function discovery now lives in framework.py (the interprocedural
# reach pass seeds from it); `index.reached_in(module)` supersedes the old
# per-module closure — a function called from traced code in ANOTHER module
# is now visible here too.
# ---------------------------------------------------------------------------

_find_local_def = find_local_def
_traced_closure = traced_closure


def _fns_under_trace(module: Module, index: ProjectIndex):
    """Every function node in `module` that runs under a trace. The project
    reach map when the index carries one (normal lint runs), with the
    module-local closure as the jax-free fallback for direct rule calls."""
    if index.reach:
        return [r.info.node for r in index.reached_in(module)]
    return list(traced_closure(module, traced_functions(module)))


# ---------------------------------------------------------------------------
# EFF001 — side effects under trace
# ---------------------------------------------------------------------------

def check_eff001(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    findings: List[Finding] = []
    closure = _fns_under_trace(module, index)
    seen: Set[int] = set()
    for fn in closure:
        for node in walk_scope(fn):
            if id(node) in seen:
                continue
            seen.add(id(node))
            msg = None
            if isinstance(node, ast.Global):
                msg = ("`global` mutation inside a traced function runs at "
                       "trace time only — it will NOT re-run per step once "
                       "compiled; thread state through the function's "
                       "arguments/outputs instead")
            elif isinstance(node, ast.Call):
                resolved = module.resolve(node.func)
                if isinstance(node.func, ast.Name) and node.func.id == "print":
                    msg = ("print() under trace fires once at trace time, "
                           "then never again — use jax.debug.print for "
                           "runtime values")
                elif resolved and resolved.startswith("time.") \
                        and resolved.split(".", 1)[1] in (
                            "time", "perf_counter", "monotonic",
                            "process_time", "sleep"):
                    msg = (f"{resolved}() under trace is evaluated once at "
                           f"trace time and baked into the compiled program "
                           f"as a constant — time OUTSIDE the jitted "
                           f"function (after jax.block_until_ready)")
                elif resolved and resolved.startswith("numpy.random."):
                    msg = (f"{resolved}() under trace draws host randomness "
                           f"ONCE and bakes it in as a constant — every "
                           f"compiled step reuses the same values; use "
                           f"jax.random with a threaded key")
                elif resolved and resolved.startswith("random.") \
                        and "random" in module.import_roots:
                    msg = (f"{resolved}() under trace is trace-time host "
                           f"randomness baked in as a constant — use "
                           f"jax.random with a threaded key")
            if msg:
                f = module.finding(node, "EFF001", SEVERITY_WARNING, msg)
                if f:
                    findings.append(f)
    return findings


# ---------------------------------------------------------------------------
# TRC001 — concrete boolean on a likely tracer
# ---------------------------------------------------------------------------

# shared with the interprocedural reach pass, which applies the same policy
# when deciding whether a call argument propagates taint into a callee
from .framework import (SAFE_ATTRS, SAFE_CALLS,  # noqa: E402,F401
                        unsafe_tracer_use as _unsafe_tracer_use)


def _expr_tainted(module: Module, expr: ast.AST, tainted: Set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in tainted \
                and isinstance(node.ctx, ast.Load):
            if _unsafe_tracer_use(module, node, expr):
                return True
    return False


def _check_traced_fn(module: Module, fn: ast.AST, findings: List[Finding],
                     initial: Optional[Set[str]] = None) -> None:
    args = getattr(fn, "args", None)
    if args is None:
        return
    if initial is not None:
        # interprocedural entry: only the params traced call sites actually
        # pass tracer-derived values to (framework.compute_trace_reach)
        tainted: Set[str] = set(initial)
    else:
        params = [a.arg for a in args.posonlyargs + args.args
                  + args.kwonlyargs]
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        tainted = set(params)
        if args.vararg:
            tainted.add(args.vararg.arg)

    def visit(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, SCOPE_TYPES):
                continue  # nested defs get their own _check_traced_fn pass
            if isinstance(stmt, ast.Assign):
                hot = _expr_tainted(module, stmt.value, tainted)
                for t in stmt.targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            (tainted.add if hot
                             else tainted.discard)(sub.id)
            elif isinstance(stmt, ast.AugAssign):
                if isinstance(stmt.target, ast.Name) \
                        and _expr_tainted(module, stmt.value, tainted):
                    tainted.add(stmt.target.id)
            elif isinstance(stmt, (ast.If, ast.While)):
                if _expr_tainted(module, stmt.test, tainted):
                    kind = "while" if isinstance(stmt, ast.While) else "if"
                    f = module.finding(
                        stmt, "TRC001", SEVERITY_ERROR,
                        f"`{kind}` on a value derived from a traced "
                        f"function's arguments: under jit this is a tracer, "
                        f"and bool(tracer) raises TracerBoolConversionError "
                        f"(or silently freezes the branch with "
                        f"static_argnums) — use jax.numpy.where / "
                        f"jax.lax.cond / jax.lax.select instead")
                    if f:
                        findings.append(f)
                visit(stmt.body)
                visit(getattr(stmt, "orelse", []))
                continue
            elif isinstance(stmt, ast.For):
                if isinstance(stmt.target, ast.Name):
                    (tainted.add if _expr_tainted(module, stmt.iter, tainted)
                     else tainted.discard)(stmt.target.id)
                visit(stmt.body)
                visit(stmt.orelse)
                continue
            elif isinstance(stmt, (ast.With, ast.Try)):
                for field in ("body", "orelse", "finalbody"):
                    visit(getattr(stmt, field, []))
                for handler in getattr(stmt, "handlers", []):
                    visit(handler.body)
                continue

    body = fn.body if isinstance(fn.body, list) else []  # Lambda: no stmts
    visit(body)


def check_trc001(module: Module, index: ProjectIndex,
                 config: Config) -> List[Finding]:
    findings: List[Finding] = []
    if index.reach:
        for entry in index.reached_in(module):
            if isinstance(entry.info.node, ast.Lambda):
                continue  # a lambda body has no if/while statements
            # seeds carry their own taint set too: all params EXCEPT the
            # trace-time statics a functools.partial binds at the call
            # site (framework.partial_bound_statics)
            _check_traced_fn(module, entry.info.node, findings,
                             initial=entry.tainted)
    else:
        for fn in traced_closure(module, traced_functions(module)):
            if isinstance(fn, ast.Lambda):
                continue
            _check_traced_fn(module, fn, findings)
    return findings


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

from .concurrency import (check_lck001, check_lck002,  # noqa: E402
                          check_lck003, check_lck004, check_thr001)
from .rules_dtype import check_dty001, check_dty002  # noqa: E402
from .rules_rng import check_rng001, check_rng002  # noqa: E402
from .rules_sharding import check_shd001, check_shd002  # noqa: E402

ALL_RULES = {
    "DON001": (SEVERITY_ERROR, check_don001,
               "argument read again after being passed to a "
               "donate_argnums-jitted callable"),
    "JIT001": (SEVERITY_ERROR, check_jit001,
               "jax.jit built inside a loop or invoked immediately "
               "(per-call retrace)"),
    "SYNC001": (SEVERITY_WARNING, check_sync001,
                "host synchronization (.item()/float()/np.asarray/"
                "jax.device_get) inside a hot training loop"),
    "EFF001": (SEVERITY_WARNING, check_eff001,
               "host side effect (print/time/np.random/global) inside a "
               "traced function"),
    "TRC001": (SEVERITY_ERROR, check_trc001,
               "Python bool of a tracer-derived value (if/while under "
               "trace)"),
    "RNG001": (SEVERITY_ERROR, check_rng001,
               "PRNG key consumed twice without an intervening "
               "split/fold_in rebind"),
    "RNG002": (SEVERITY_WARNING, check_rng002,
               "traced step consumes its rng without deriving it from "
               "state.step (scan-safe reproducibility)"),
    "DTY001": (SEVERITY_WARNING, check_dty001,
               "full-precision value reaches the model apply fn under a "
               "declared bf16 compute policy"),
    "DTY002": (SEVERITY_WARNING, check_dty002,
               "host-side float32 upcast at a jit/device_put boundary "
               "(4x transfer bytes)"),
    "SHD001": (SEVERITY_ERROR, check_shd001,
               "mesh-axis name not defined by any mesh constructed in "
               "the project"),
    "SHD002": (SEVERITY_WARNING, check_shd002,
               "device_put without an explicit sharding inside a hot "
               "train/serve loop"),
    "LCK001": (SEVERITY_ERROR, check_lck001,
               "unguarded write to lock-guarded shared state from "
               "thread-reachable code"),
    "LCK002": (SEVERITY_ERROR, check_lck002,
               "non-atomic read-modify-write (+=, d[k]=, .append) on "
               "lock-guarded shared state outside its guard"),
    "LCK003": (SEVERITY_ERROR, check_lck003,
               "lock-order cycle: two locks acquired in opposite orders "
               "(deadlock)"),
    "LCK004": (SEVERITY_WARNING, check_lck004,
               "blocking call (HTTP/socket I/O, subprocess, untimed "
               "result/get/join/wait, sleep) while holding a lock"),
    "THR001": (SEVERITY_WARNING, check_thr001,
               "thread started with neither daemon=True nor a reachable "
               "join()"),
}
