"""`python -m deepvision_tpu <subcommand>` — repo-level maintenance CLI.

    # audit checkpoint integrity across a run dir (or a whole runs/ root)
    python -m deepvision_tpu fsck runs/resnet50
    python -m deepvision_tpu fsck runs/resnet50 --quarantine   # repair

fsck walks every checkpoint directory it can find under the given path (the
path itself when it holds committed epochs, its `ckpt/` child for a run
workdir, else every `<child>/ckpt` one level down) and prints one line per
epoch, including the mesh topology each epoch was SAVED under (the shape an
elastic restore reshards from — docs/FAILURES.md "Elastic resume"):

    OK                epoch 3   1.2 MB  manifest=ab12cd34  mesh=data:4,model:2
    CORRUPT           epoch 2   state/d/...: content hash mismatch (bit rot?)
    MISSING-MANIFEST  epoch 1   no integrity manifest
    QUARANTINED       corrupt-2

Exit codes (the lint-CLI convention): 0 = nothing corrupt, 1 = at least one
CORRUPT epoch found (even if `--quarantine` just repaired it — rerun to get
a clean 0), 2 = usage error (path does not exist). `--quarantine` renames
corrupt epochs (and missing-manifest epochs in dirs whose siblings carry
manifests — an interrupted save) to `corrupt-<epoch>/` so restores stop
considering them; `tools/preflight.py` runs the same audit as its fsck
check. `--format json` emits ONE machine-readable JSON document (summary +
full per-epoch reports, no human lines) with the same exit codes — the
jaxlint/jaxvet machine-readable contract for CI and fleet tooling.
Contract: docs/FAILURES.md.

The audit is file-level (sizes + sha256 against the manifest) and stdlib-
only — no jax import, so it is safe and fast on a login host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence


def _human_bytes(n) -> str:
    if n is None:
        return "?"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0
    return f"{n:.1f} TB"


def _fmt_mesh(mesh) -> str:
    """Compact saved-topology tag for the per-epoch line: 'data:4,model:2'
    (size-1 axes elided — they place nothing); '' when the manifest predates
    the elastic layer. Pure dict formatting — fsck stays jax-free."""
    axes = (mesh or {}).get("axes") or {}
    shown = {k: v for k, v in axes.items() if v > 1} or axes
    return ",".join(f"{k}:{v}" for k, v in shown.items())


def _cmd_fsck(args: argparse.Namespace) -> int:
    from .core import integrity

    machine = args.format == "json"
    path = os.path.abspath(args.path)
    if not os.path.isdir(path):
        print(f"fsck: {args.path!r} is not a directory", file=sys.stderr)
        return 2
    ckpt_dirs = integrity.find_checkpoint_dirs(path)
    if not ckpt_dirs:
        if machine:
            print(json.dumps({"fsck": "ok", "checkpoint_dirs": 0,
                              "epochs_audited": 0, "corrupt": 0,
                              "quarantined": False, "reports": []}))
        else:
            print(f"fsck: no checkpoint directories under {args.path} "
                  f"(nothing to audit)")
        return 0
    all_records = []
    n_corrupt = 0
    for d in ckpt_dirs:
        records = integrity.audit(d, quarantine=args.quarantine)
        all_records.append({"dir": d, "epochs": records})
        if not machine:
            print(f"== {d}")
            if not records:
                print("   (no committed epochs)")
        for r in records:
            status = r["status"].upper().replace("_", "-")
            if r["status"] == integrity.OK:
                detail = (f"{_human_bytes(r.get('total_bytes'))}  "
                          f"manifest={r.get('manifest_sha256', '')[:12]}")
                mesh = _fmt_mesh(r.get("mesh"))
                if mesh:
                    detail += f"  mesh={mesh}"
            elif r["status"] == integrity.QUARANTINED:
                detail = r["detail"]
            else:
                detail = r["detail"]
                if "quarantined_to" in r:
                    detail += f" -> {r['quarantined_to']}"
            epoch = f"epoch {r['epoch']}" if r["epoch"] is not None else ""
            if not machine:
                print(f"{status:17s} {epoch:9s} {detail}")
            n_corrupt += r["status"] == integrity.CORRUPT
    summary = {"fsck": "corrupt" if n_corrupt else "ok",
               "checkpoint_dirs": len(ckpt_dirs),
               "epochs_audited": sum(
                   1 for d in all_records for r in d["epochs"]
                   if r["epoch"] is not None),
               "corrupt": n_corrupt,
               "quarantined": args.quarantine and n_corrupt > 0}
    if machine or args.json:
        summary["reports"] = all_records
    print(json.dumps(summary))
    return 1 if n_corrupt else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m deepvision_tpu",
        description="Repo-level maintenance subcommands (see also: "
                    "-m deepvision_tpu.serve, -m deepvision_tpu.lint)")
    sub = parser.add_subparsers(dest="command", required=True)
    fsck = sub.add_parser(
        "fsck", help="audit checkpoint integrity across a run directory",
        description="Verify every committed checkpoint epoch against its "
                    "integrity manifest (file sizes + sha256). Exit 0 = "
                    "clean, 1 = corruption found, 2 = usage error.")
    fsck.add_argument("path", help="run workdir, its ckpt/ dir, or a runs/ "
                                   "root to scan one level deep")
    fsck.add_argument("--quarantine", action="store_true",
                      help="rename corrupt epochs to corrupt-<epoch>/ so "
                           "restores stop considering them (repair)")
    fsck.add_argument("--json", action="store_true",
                      help="append full per-epoch reports to the summary "
                           "JSON line (text mode; see also --format json)")
    fsck.add_argument("--format", choices=["text", "json"], default="text",
                      help="'json' emits one machine-readable document "
                           "(summary + per-epoch reports incl. the saved "
                           "mesh topology) and no human lines — the "
                           "jaxlint/jaxvet CLI contract; exit codes "
                           "unchanged (0/1/2)")
    fsck.set_defaults(fn=_cmd_fsck)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
