"""The retrain controller: one confirmed drift event → one bounded episode.

State machine (FLYWHEEL_STATES, one-hot on /metrics):

    monitoring ──drift──> drift_detected ──> finetuning ──> gating ──┐
        ^                                                            │
        │<── promoted (rebaseline, reset backoff) <──────────────────┤
        │<── refused / rolled_back (exponential backoff, retry) <────┤
        │                                                            │
        └───────── circuit_open (max_attempts failures: STOP) <──────┘

Everything downstream of detection is REUSE, not reimplementation:

- **Fine-tune**: a bounded number of epochs through the existing trainer
  family (`trainer_class_for_config`), resumed from the newest committed
  epoch in the served model's own run dir; `epoch_on_device` is attempted
  and falls back per the trainer's own eligibility rules. Each retry
  commits a NEW epoch, so the reloader's permanent per-epoch refusal
  cache never blocks a retry.
- **Gate + canary + rollback**: the committed candidate goes through a
  private `WeightReloader.check_once()` over exactly this model, which
  verifies integrity, restores, and delegates to the PR 11
  `PromotionController` — shadow eval on the pinned shard, metric-delta
  gate, canary window, auto-rollback. When the engine serves int8, the
  swap re-quantizes under the pinned calibration plan automatically
  (serve/quantize.py) — same as any hot reload.
- **Backoff + circuit**: a refused or rolled-back candidate schedules the
  next attempt at `backoff_base_s * 2^(failures-1)` (capped at
  `backoff_max_s`); `max_attempts` consecutive failures open the retrain
  circuit — the flywheel STOPS retraining, alerts loudly on stderr and
  the resilience stream, and an operator must `reset_circuit()`.

The `flywheel_id` the monitor mints at the drift event is carried through
every resilience event, every span (`flywheel_finetune`/`flywheel_gate`
plus the trainer's own spans via `arm_tracing`), the promotion
controller's decision records, and /healthz — one grep reconstructs the
whole episode (docs/FAILURES.md "Flywheel decisions").

Serving keeps flowing throughout: fine-tune and gating run on the
flywheel thread; request threads only ever see the monitor's cheap
sample-copy tap and the canary routing the promotion pipeline already
imposes. The rehearsal (tests/test_flywheel.py, preflight `flywheel`)
pins zero recompiles on the serve path across a full episode.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Iterable, Optional

from ..core import integrity
from ..core.resilience import log_resilience_event
from ..utils.faults import FaultInjector
from .drift import DriftMonitor

# every state the controller can report; obs/export.py emits the one-hot
# `deepvision_serve_flywheel_state` gauge over exactly this tuple
FLYWHEEL_STATES = ("monitoring", "drift_detected", "finetuning", "gating",
                   "promoted", "refused", "rolled_back", "circuit_open")

# promotion decisions that map onto the two failure states
_ROLLBACK_DECISIONS = ("rolled_back_canary", "rolled_back_abort")


class FlywheelController:
    """Owns one served model's drift→retrain→promote loop. Requires the
    model to be workdir-backed (somewhere to commit fine-tuned epochs) and
    promotion-gated (`sm.promoter` — the flywheel never swaps weights
    without the gate). Attaches itself as `sm.flywheel` for /healthz."""

    def __init__(self, sm, monitor: Optional[DriftMonitor] = None, *,
                 finetune_epochs: int = 1,
                 finetune_batches: int = 4,
                 max_attempts: int = 3,
                 backoff_base_s: float = 1.0,
                 backoff_max_s: float = 60.0,
                 tick_every_s: float = 0.5,
                 data_fn: Optional[Callable[[int], Iterable]] = None,
                 logger=None, tracer=None,
                 faults: Optional[FaultInjector] = None,
                 **monitor_kwargs):
        if not sm.workdir:
            raise ValueError(
                f"model {sm.name!r} is served with static weights (no "
                f"workdir) — the flywheel needs a run dir to commit "
                f"fine-tuned epochs into")
        if sm.promoter is None:
            raise ValueError(
                f"model {sm.name!r} has no promotion controller — the "
                f"flywheel only ships candidates through the shadow/"
                f"canary gate (arm --promote-gate first)")
        if finetune_epochs < 1:
            raise ValueError(f"finetune_epochs must be >= 1, got "
                             f"{finetune_epochs}")
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got "
                             f"{max_attempts}")
        self.sm = sm
        self.finetune_epochs = int(finetune_epochs)
        self.finetune_batches = int(finetune_batches)
        self.max_attempts = int(max_attempts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.tick_every_s = float(tick_every_s)
        self.logger = logger
        self.tracer = tracer
        self.faults = faults if faults is not None else FaultInjector.from_env()
        self._data_fn = data_fn
        self.monitor = monitor if monitor is not None else DriftMonitor(
            sm, logger=logger, faults=self.faults, **monitor_kwargs)

        # the gating path: a PRIVATE reloader over exactly this model, so
        # `check_once()` verifies/restores/proposes the freshly committed
        # epoch on the flywheel thread without racing the server's own
        # poller cadence
        from ..serve.reload import WeightReloader
        self._reloader = WeightReloader([sm], poll_every_s=0, logger=logger)

        self._lock = threading.Lock()
        self.state = "monitoring"
        self.failures = 0              # consecutive failed episodes
        self.episodes = 0              # drift events acted on
        self.counters = {"retrains": 0, "promoted": 0, "refused": 0,
                         "rolled_back": 0, "circuit_opened": 0}
        self.last_decision: Optional[str] = None
        self.last_flywheel_id: Optional[str] = None
        self._backoff_until = 0.0      # monotonic deadline for next attempt
        self._events = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        sm.flywheel = self

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FlywheelController":
        if self._thread is None and self.tick_every_s > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"flywheel-{self.sm.name}")
            self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_every_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self._log(f"tick failed (will retry): {e!r}")

    # -- one tick ----------------------------------------------------------

    def tick(self) -> str:
        """One control step: let the monitor evaluate a window; if a drift
        trigger is pending (and the circuit is closed and any backoff has
        expired), run one full episode synchronously. Returns the state
        after the tick — the test/preflight handle, and exactly what the
        thread calls."""
        if self.state == "circuit_open":
            return self.state
        self.monitor.tick()
        if self.monitor.triggered_id is None:
            if self.state == "monitoring" or self._backing_off():
                return self.state
            # trigger cleared without an episode (operator reset): idle
            self._set_state("monitoring")
            return self.state
        if self._backing_off():
            return self.state
        return self._run_episode(self.monitor.triggered_id)

    def _backing_off(self) -> bool:
        return time.monotonic() < self._backoff_until

    # -- the episode -------------------------------------------------------

    def _run_episode(self, fid: str) -> str:
        with self._lock:
            self.episodes += 1
            self.last_flywheel_id = fid
        self._set_state("drift_detected", fid)
        self._log(f"drift confirmed ({fid}): input_shift="
                  f"{self.monitor.last_input_shift:.3f} watch_decay="
                  f"{self.monitor.last_watch_decay:.3f} — starting a "
                  f"bounded fine-tune (attempt "
                  f"{self.failures + 1}/{self.max_attempts})")
        try:
            self._set_state("finetuning", fid)
            with self._span("flywheel_finetune", fid):
                epoch = self._finetune(fid)
            with self._lock:
                self.counters["retrains"] += 1
            self._set_state("gating", fid,
                            extra={"flywheel_candidate_epoch": float(epoch)})
            promoter = self.sm.promoter
            promoter.flywheel_id = fid
            try:
                with self._span("flywheel_gate", fid, epoch=epoch):
                    swapped = self._reloader.check_once()
            finally:
                promoter.flywheel_id = None
            decision = (promoter.history[-1]["decision"]
                        if promoter.history else None)
        except Exception as e:  # noqa: BLE001 — a failed fine-tune is a
            # failed episode (backoff/circuit), never a dead control loop
            self._log(f"episode {fid} failed before the gate: {e!r}")
            return self._failed(fid, "refused", f"finetune_error: {e!r}")
        with self._lock:
            self.last_decision = decision
        if swapped:
            return self._promoted(fid, epoch)
        if decision in _ROLLBACK_DECISIONS:
            return self._failed(fid, "rolled_back", decision)
        return self._failed(fid, "refused", decision or "no_candidate")

    def _promoted(self, fid: str, epoch: int) -> str:
        with self._lock:
            self.counters["promoted"] += 1
            self.failures = 0
            self._backoff_until = 0.0
        # the retrained weights now DEFINE normal: adopt the drifted
        # window's moments as the reference and re-score the baseline, or
        # the same shift re-triggers forever
        self.monitor.rebaseline()
        self._set_state("promoted", fid,
                        extra={"flywheel_promoted_epoch": float(epoch)})
        self._log(f"episode {fid}: candidate epoch {epoch} PROMOTED "
                  f"through the shadow/canary gate — rebaselined the "
                  f"drift reference; back to monitoring")
        self._set_state("monitoring", fid)
        return "promoted"

    def _failed(self, fid: str, state: str, decision: str) -> str:
        with self._lock:
            self.counters["rolled_back" if state == "rolled_back"
                          else "refused"] += 1
            self.failures += 1
            failures = self.failures
        if failures >= self.max_attempts:
            with self._lock:
                self.counters["circuit_opened"] += 1
            self._set_state("circuit_open", fid,
                            extra={"flywheel_failures": float(failures)})
            self._log(f"episode {fid}: {decision} — {failures} consecutive "
                      f"failed retrain attempts: RETRAIN CIRCUIT OPEN. The "
                      f"flywheel stops retraining this model; the incumbent "
                      f"keeps serving. Investigate the drift + refusals "
                      f"(docs/FAILURES.md 'Flywheel decisions'), then "
                      f"reset_circuit() / restart to re-arm.")
            return "circuit_open"
        backoff = min(self.backoff_max_s,
                      self.backoff_base_s * (2.0 ** (failures - 1)))
        with self._lock:
            self._backoff_until = time.monotonic() + backoff
        # drift is still real: keep the trigger armed via a fresh streak so
        # the next attempt re-confirms it instead of firing blind
        self.monitor.reset_trigger()
        self._set_state(state, fid,
                        extra={"flywheel_backoff_s": round(backoff, 3),
                               "flywheel_failures": float(failures)})
        self._log(f"episode {fid}: {decision} — incumbent keeps serving; "
                  f"retry {failures + 1}/{self.max_attempts} in "
                  f"{backoff:.1f}s (exponential backoff)")
        return state

    # -- the bounded fine-tune ---------------------------------------------

    def _finetune(self, fid: str) -> int:
        """Resume the served model's own run dir from its newest committed
        epoch, train `finetune_epochs` more, commit them (manifested —
        core/integrity), and return the newest committed epoch number.
        Runs entirely on the flywheel thread."""
        import os

        from ..configs import trainer_class_for_config

        ckpt_dir = os.path.join(self.sm.workdir, "ckpt")
        committed = integrity.committed_epochs(ckpt_dir)
        base = max(committed) if committed else 0
        trainer_cls = trainer_class_for_config(self.sm.name)
        if trainer_cls is None:
            raise ValueError(f"config {self.sm.name!r} has no supervised "
                             f"trainer — the flywheel cannot fine-tune it")
        cfg = self._finetune_config(base)
        trainer = None
        try:
            try:
                trainer = trainer_cls(cfg, workdir=self.sm.workdir)
            except ValueError:
                # epoch_on_device ineligible for this config (accumulation,
                # sharding, ...): the staged per-batch loop always works
                cfg = self._finetune_config(base, on_device=False)
                trainer = trainer_cls(cfg, workdir=self.sm.workdir)
            if self.tracer is not None:
                trainer.arm_tracing(tracer=self.tracer)
            trainer.init_state(self.sm.engine.example_shape)
            got = trainer.resume()
            start = (got + 1) if got is not None else 1
            for ep in range(start, start + self.finetune_epochs):
                with self._span("flywheel_train_epoch", fid, epoch=ep):
                    trainer.train_epoch(ep, self._data(ep))
                trainer.ckpt.save(ep, trainer.state, {"best_metric": 0.0})
                last = ep
            trainer.ckpt.flush()
        finally:
            if trainer is not None:
                # close() would re-export the shared tracer; the server owns
                # that — drop the trace_out handle first
                trainer._trace_out = None
                trainer.close()
        return last

    def _finetune_config(self, base: int, on_device: bool = True):
        """The bounded-budget training config: the model's own config with
        just enough epochs for this episode, a constant LR (a fine-tune
        must not replay the cosine ramp), and the whole-epoch on-device
        path when the trainer deems it eligible."""
        from ..configs import get_config
        from ..core.config import ScheduleConfig
        return get_config(self.sm.name).replace(
            total_epochs=base + self.finetune_epochs,
            epoch_on_device=on_device,
            epoch_shuffle=False,
            schedule=ScheduleConfig(name="constant"))

    def _data(self, epoch: int) -> Iterable:
        """One epoch's fine-tune batches. Production passes `data_fn` (a
        real stream reflecting the drifted distribution); the synthetic
        default keeps the loop closed-loop testable with no data on disk —
        same philosophy as the pinned shard."""
        if self._data_fn is not None:
            return self._data_fn(epoch)
        cfg = self.monitor.cfg
        h = self.sm.engine.example_shape[0]
        if cfg.family == "classification":
            from ..data.synthetic import SyntheticClassification
            return SyntheticClassification(
                cfg.batch_size, image_size=h, channels=cfg.data.channels,
                num_classes=cfg.data.num_classes,
                num_batches=self.finetune_batches, seed=epoch)
        if cfg.family == "segmentation":
            from ..data.segmentation import SyntheticSegmentation
            return SyntheticSegmentation(
                cfg.batch_size, image_size=h, channels=cfg.data.channels,
                num_classes=cfg.data.num_classes,
                num_batches=self.finetune_batches, seed=epoch)
        raise ValueError(
            f"no synthetic fine-tune stream for family {cfg.family!r} — "
            f"pass data_fn= to FlywheelController for {self.sm.name!r}")

    # -- operator handles --------------------------------------------------

    def reset_circuit(self) -> None:
        """Re-arm an open retrain circuit (operator action after fixing
        whatever made candidates keep failing). Clears the failure streak
        and the monitor's trigger; drift must re-confirm through a full
        hysteresis streak before the next episode."""
        with self._lock:
            self.failures = 0
            self._backoff_until = 0.0
            if self.state == "circuit_open":
                self.state = "monitoring"
        self.monitor.reset_trigger()
        self._log("retrain circuit reset — monitoring")

    def describe(self) -> dict:
        """The /healthz flywheel record: state machine + episode counters
        + the drift monitor's evidence."""
        with self._lock:
            backoff_left = max(0.0, self._backoff_until - time.monotonic())
            return {
                "state": self.state,
                "episodes": self.episodes,
                "failures": self.failures,
                "max_attempts": self.max_attempts,
                "backoff_s": round(backoff_left, 3),
                "counters": dict(self.counters),
                "last_decision": self.last_decision,
                "flywheel_id": self.last_flywheel_id,
                "drift": self.monitor.describe(),
            }

    # -- plumbing ----------------------------------------------------------

    def _set_state(self, state: str, fid: Optional[str] = None,
                   extra: Optional[dict] = None) -> None:
        assert state in FLYWHEEL_STATES, state
        with self._lock:
            self.state = state
            self._events += 1
            step = self._events
        log_resilience_event(
            self.logger, step,
            {f"flywheel_{state}": 1.0, **(extra or {})},
            flywheel_id=fid)

    def _span(self, name: str, fid: str, **args):
        """A controller span carrying the episode id; a no-op context when
        the server runs without tracing."""
        if self.tracer is not None and self.tracer.enabled:
            return self.tracer.span(name, cat="flywheel",
                                    flywheel_id=fid, model=self.sm.name,
                                    **args)
        import contextlib
        return contextlib.nullcontext({})

    def _log(self, msg: str) -> None:
        # stderr like the reload/promote layers: flywheel decisions must be
        # loud on the replica that took them
        print(f"[flywheel:{self.sm.name}] {msg}", file=sys.stderr,
              flush=True)


def attach_flywheels(fleet, *, logger=None, tracer=None,
                     warn: Optional[Callable[[str], None]] = None,
                     **kwargs) -> int:
    """Attach a FlywheelController to every promotion-gated, workdir-backed
    model in the fleet (the serve CLI's `--flywheel-every` wiring). Models
    that don't qualify are skipped with a warning — they keep whatever
    reload/promotion path they already have. Returns how many models got a
    controller (callers `start()` them)."""
    n = 0
    for sm in fleet:
        try:
            FlywheelController(sm, logger=logger, tracer=tracer, **kwargs)
            n += 1
        except ValueError as e:
            if warn is not None:
                warn(f"[serve:{sm.name}] flywheel skipped: {e}")
    return n
