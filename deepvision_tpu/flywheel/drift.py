"""Drift detection for one served model: pinned reference vs live traffic.

The monitor owns the *detect* half of the flywheel loop. It compares two
streams that share every primitive with the existing gates, so the
comparison can never disagree with them on recipe:

- **Reference**: the pinned calibration shard (`core/scoring.pinned_shard`)
  — the byte-deterministic batch the promotion gate and the int8
  calibration already replay. Its per-channel input moments
  (`core/scoring.input_moments`) are the reference distribution, and the
  family's watched metric scored on it at arm time is the reference
  quality baseline.
- **Live**: a bounded reservoir of inputs sampled at the batcher's
  per-batch observer tap (`DynamicBatcher` passes `sample=` references;
  the monitor COPIES the few rows it keeps, so retained samples never pin
  whole request batches). Once a full window accumulates, `tick()` reduces
  it with the same `input_moments` and scores the live generation on the
  pinned shard again — watch decay is baseline minus current.

A window *breaches* when the input moment shift exceeds `input_gate`
(reference-σ units, `core/scoring.moment_shift`) or the watch decay
exceeds `watch_gate`. Detection needs `hysteresis_windows` CONSECUTIVE
breaches: a transient spike (one hot batch, a brief upstream glitch)
resets the streak and never triggers. On trigger the monitor mints the
episode's `flywheel_id` — the correlation key every downstream decision
of that drift→retrain→promote episode carries (core/resilience.py).

The observer tap is CHAINED, not stolen: the promotion controller owns
`batcher.observer` for its canary comparison, so the monitor saves the
previous observer and calls it first from its own. Ingest (dispatcher
worker threads) only appends copies under a lock; all evaluation happens
in `tick()` on the flywheel controller's thread — detection work never
rides the dispatch path, so watching for drift sheds no healthy traffic.

`DEEPVISION_FAULT_DRIFT_SHIFT=<window>:<magnitude>` (utils/faults.py)
rehearses the whole loop deterministically: from the armed window on,
ingested samples get a constant additive shift, which moves the window
moments without touching real traffic.
"""

from __future__ import annotations

import threading
import uuid
from typing import List, Optional

import numpy as np

from ..core import scoring
from ..core.resilience import log_resilience_event
from ..utils.faults import FaultInjector


class DriftMonitor:
    """Streaming drift detector for one `ServedModel`. Construct it AFTER
    the promotion controller (observer chaining preserves whatever tap was
    installed first); `tick()` is driven by the flywheel controller's
    thread, tests, or preflight — never by request threads."""

    def __init__(self, sm, cfg=None, *,
                 window_examples: int = 32,
                 sample_per_batch: int = 4,
                 input_gate: float = 0.5,
                 watch_gate: float = 0.1,
                 hysteresis_windows: int = 3,
                 eval_examples: int = 64,
                 seed: int = scoring.DEFAULT_SHARD_SEED,
                 logger=None,
                 faults: Optional[FaultInjector] = None):
        if window_examples <= 0:
            raise ValueError(f"window_examples must be > 0, got "
                             f"{window_examples}")
        if sample_per_batch <= 0:
            raise ValueError(f"sample_per_batch must be > 0, got "
                             f"{sample_per_batch}")
        if hysteresis_windows < 1:
            raise ValueError(f"hysteresis_windows must be >= 1, got "
                             f"{hysteresis_windows} — 1 means every "
                             f"breaching window triggers")
        from ..configs import get_config
        self.sm = sm
        self.cfg = cfg if cfg is not None else get_config(sm.name)
        if self.cfg.family not in scoring.GATED_FAMILIES:
            raise ValueError(
                f"config {sm.name!r} (family {self.cfg.family!r}) has no "
                f"predict-side watch metric — the flywheel monitors "
                f"families {scoring.GATED_FAMILIES}")
        self.window_examples = int(window_examples)
        self.sample_per_batch = int(sample_per_batch)
        self.input_gate = float(input_gate)
        self.watch_gate = float(watch_gate)
        self.hysteresis_windows = int(hysteresis_windows)
        self.watch_name = scoring.watch_metric_name(self.cfg)
        self.logger = logger
        self.faults = faults if faults is not None else FaultInjector.from_env()

        # the pinned reference: same shard recipe as the promotion gate and
        # the int8 calibration, byte-deterministic per (config, seed)
        self._ref_images, self._ref_targets = scoring.pinned_shard(
            self.cfg, image_size=sm.engine.example_shape[0],
            input_dtype=sm.engine.input_dtype,
            examples=int(eval_examples), seed=int(seed))
        self.ref_mean, self.ref_std = scoring.input_moments(self._ref_images)
        # watch baseline is captured lazily at first evaluation so building
        # a monitor costs no predict; from then on it only moves on
        # rebaseline()
        self.baseline_watch: Optional[float] = None

        self._lock = threading.Lock()
        self._rows: List[np.ndarray] = []   # copied sample rows, <= window
        self._last_trace_ref: Optional[str] = None
        self._last_moments = None           # (mean, std) of the last window
        self.windows = 0                    # full windows evaluated
        self.breaches = 0                   # windows over either gate
        self.consecutive = 0                # current breach streak
        self.triggered_id: Optional[str] = None
        self.last_input_shift = 0.0
        self.last_watch_decay = 0.0
        self._events = 0

        # chain the batcher tap: the promotion controller (or any earlier
        # observer) keeps seeing every batch through us
        self._prev_observer = sm.batcher.observer
        sm.batcher.observer = self._observe

    # -- ingest (dispatcher worker threads: copy + append, nothing else) ---

    def _observe(self, generation: str, latencies_s, dispatch_s, error,
                 sample=None) -> None:
        if self._prev_observer is not None:
            self._prev_observer(generation, latencies_s, dispatch_s, error,
                                sample=sample)
        if error is not None or sample is None or generation != "live":
            return                      # canary traffic would skew moments
        images = sample.get("images")
        if images is None or len(images) == 0:
            return
        rows = np.asarray(images[:self.sample_per_batch], np.float32).copy()
        if rows.ndim != 4:
            return                      # not an image batch we can moment
        with self._lock:
            shift = self.faults.drift_shift(self.windows)
            if shift:
                rows = rows + np.float32(shift)
            room = self.window_examples - len(self._rows)
            if room <= 0:
                return                  # window full: wait for a tick
            self._rows.extend(rows[:room])
            if sample.get("trace_ref"):
                self._last_trace_ref = sample["trace_ref"]

    # -- evaluation (controller thread / tests / preflight) ----------------

    def _ensure_baseline(self) -> float:
        if self.baseline_watch is None:
            self.baseline_watch = self._score_live()
        return self.baseline_watch

    def _score_live(self) -> float:
        """The live generation's watched metric on the pinned shard — the
        exact replay the promotion gate's shadow eval runs, through the
        same compiled bucket programs (zero recompiles)."""
        out = self.sm.engine.predict(self._ref_images, generation=None)
        return scoring.score_serving_outputs(self.cfg, out,
                                             self._ref_targets)

    def tick(self) -> Optional[str]:
        """Evaluate one full window if one is buffered. Returns the minted
        `flywheel_id` iff THIS call completed the hysteresis streak;
        otherwise None (including while already triggered). Every evaluated
        window lands one event on the `resilience_` stream."""
        with self._lock:
            if len(self._rows) < self.window_examples:
                return None
            window = np.stack(self._rows[:self.window_examples])
            self._rows.clear()
            trace_ref = self._last_trace_ref
        baseline = self._ensure_baseline()
        mean, std = scoring.input_moments(window)
        input_shift = scoring.moment_shift(self.ref_mean, self.ref_std,
                                           mean, std)
        watch_decay = baseline - self._score_live()
        breach = (input_shift > self.input_gate
                  or watch_decay > self.watch_gate)
        minted: Optional[str] = None
        with self._lock:
            self.windows += 1
            self._last_moments = (mean, std)
            self.last_input_shift = input_shift
            self.last_watch_decay = watch_decay
            if breach:
                self.breaches += 1
                self.consecutive += 1
            else:
                self.consecutive = 0    # hysteresis: streaks only
            if (breach and self.triggered_id is None
                    and self.consecutive >= self.hysteresis_windows):
                minted = f"fw-{uuid.uuid4().hex[:12]}"
                self.triggered_id = minted
            self._events += 1
            step = self._events
        log_resilience_event(
            self.logger, step,
            {"flywheel_window": float(self.windows),
             "flywheel_input_shift": round(input_shift, 4),
             "flywheel_watch_decay": round(watch_decay, 4),
             "flywheel_breach": 1.0 if breach else 0.0,
             **({"flywheel_drift_detected": 1.0} if minted else {})},
            trace_ref=trace_ref,
            flywheel_id=minted or self.triggered_id)
        return minted

    # -- episode lifecycle (called by the flywheel controller) -------------

    def reset_trigger(self) -> None:
        """Clear the trigger and streak WITHOUT moving the reference —
        the failed-episode path: drift is still real, the monitor may
        re-confirm it (a full hysteresis streak again) for the next
        attempt."""
        with self._lock:
            self.triggered_id = None
            self.consecutive = 0
            self._rows.clear()

    def rebaseline(self) -> None:
        """Adopt the last evaluated window's moments as the new input
        reference and re-score the (just promoted) live generation as the
        new watch baseline — the promoted-episode path. Without this the
        same shift re-triggers forever: the model was retrained ON the new
        distribution, so the new distribution is now normal."""
        with self._lock:
            if self._last_moments is not None:
                self.ref_mean, self.ref_std = self._last_moments
            self.triggered_id = None
            self.consecutive = 0
            self._rows.clear()
        self.baseline_watch = self._score_live()

    def describe(self) -> dict:
        """The /healthz drift record (nested under the flywheel entry)."""
        with self._lock:
            return {
                "watch": self.watch_name,
                "baseline_watch": (round(self.baseline_watch, 4)
                                   if self.baseline_watch is not None
                                   else None),
                "window_examples": self.window_examples,
                "input_gate": self.input_gate,
                "watch_gate": self.watch_gate,
                "hysteresis_windows": self.hysteresis_windows,
                "windows": self.windows,
                "breaches": self.breaches,
                "consecutive": self.consecutive,
                "buffered": len(self._rows),
                "last_input_shift": round(self.last_input_shift, 4),
                "last_watch_decay": round(self.last_watch_decay, 4),
                "triggered_id": self.triggered_id,
            }
