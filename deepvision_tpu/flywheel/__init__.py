"""Flywheel: drift-triggered continuous training (ROADMAP item 4).

The loop that closes serve→train→serve: `DriftMonitor` (drift.py) watches
one served model's live inputs/outputs against the pinned calibration
shard, and `FlywheelController` (controller.py) turns a confirmed drift
event into a bounded fine-tune, re-gates the result through the existing
promotion pipeline, and backs off — or opens a circuit — when candidates
keep failing. Every decision of one episode carries one `flywheel_id`
across the resilience stream, spans, /healthz, and /metrics.

docs/FAILURES.md "Flywheel decisions" is the operator contract.
"""

from .controller import FLYWHEEL_STATES, FlywheelController
from .drift import DriftMonitor

__all__ = ["DriftMonitor", "FlywheelController", "FLYWHEEL_STATES"]
