"""Fused multi-head attention: a flash-style Pallas TPU kernel vs naive einsum.

Attention is the op mix XLA fuses worst: the naive lowering materializes the
(N, N) score matrix in HBM twice (once for QK^T, once for the softmax-ed
probabilities) before the PV contraction reads it back.  The kernel below
follows the tiling discipline proven in `ops/pallas_kernels.py` for the YOLO
IoU hot spot: each grid program owns one (BLOCK_Q, D) query tile plus the full
(padded) K/V panel in VMEM and runs the online-softmax recurrence over
BLOCK_K-sized key tiles — running row max `m`, running denominator `l`, and a
rescaled PV accumulator — so no (N, N) tile ever exists outside VMEM.

Invariant (see docs/ATTENTION.md): after key tile j,
    acc = sum_{i<=j} exp(s_i - m_j) @ v_i,   l = sum_{i<=j} exp(s_i - m_j) 1
and `acc / l` equals softmax(QK^T * scale) @ V exactly in infinite precision;
in f32 the reassociation error is bounded by the tests in tests/test_vit.py.

Inside the kernel, softmax statistics and both contractions accumulate in f32
regardless of input dtype (`preferred_element_type`) — VMEM-resident, so the
policy checker never sees it. The naive path instead runs its einsums AT the
operand dtype and promotes only the (elementwise) softmax to f32: explicit f32
dot outputs would push f32 cotangents through the einsum transposes and put
f32 matmuls into a declared-bf16 train step. bf16 parity between the two
lowerings is therefore a rounding story (one extra rounding of the naive
scores), bounded by tests/test_vit.py.

CPU fallback: `interpret=True` runs the same kernel under the Pallas
interpreter (tests, preflight); `impl="naive"` is the pure-XLA path.
`DEEPVISION_NO_PALLAS=1` forces naive even on TPU.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANE = 128

#: Default tile sizes. 128 keys/queries per tile keeps the score tile at
#: (128, 128) f32 = 64 KiB, far under VMEM, and aligns both axes to the lane
#: width so Mosaic never pads internally.
BLOCK_Q = 128
BLOCK_K = 128


def naive_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    scale: Optional[float] = None) -> jnp.ndarray:
    """Reference dot-product attention on (B, H, N, D) operands.

    The (N, N) score and probability matrices are materialized — this is
    the baseline the walker's bytes proxy charges for.

    Both contractions run AT the operand dtype: only the softmax is
    promoted to f32 (elementwise, so it adds no f32 matmul to a bf16
    step and its backward carries bf16 cotangents into both einsum
    transposes — jaxvet's DTYPE rule audits exactly that). The MXU
    accumulates bf16 products in f32 internally regardless, so dropping
    `preferred_element_type` here costs one rounding of the scores, which
    the bf16 parity bound in tests/test_vit.py covers.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k)
    p = jax.nn.softmax(s.astype(jnp.float32) * scale, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, n_valid: int,
                  scale: float):
    """One (BLOCK_Q, D) query tile against all key tiles, online softmax.

    q_ref: (1, 1, BLOCK_Q, Dp); k_ref/v_ref: (1, 1, Npad, Dp) — the full
    padded panel for this (batch, head) program; o_ref: (1, 1, BLOCK_Q, Dp).
    Padded key rows (index >= n_valid) are masked to -inf before the max/exp;
    padded D lanes are zero so they add nothing to either contraction.
    """
    q = q_ref[0, 0].astype(jnp.float32) * scale       # (bq, Dp)
    n_pad = k_ref.shape[2]

    def body(j, carry):
        m, l, acc = carry
        kj = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vj = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        key_idx = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(key_idx < n_valid, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)                     # rescale old running sums
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vj, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    bq = q.shape[0]
    init = (jnp.full((bq, 1), -jnp.inf, jnp.float32),
            jnp.zeros((bq, 1), jnp.float32),
            jnp.zeros(q.shape, jnp.float32))
    _, l, acc = jax.lax.fori_loop(0, n_pad // block_k, body, init)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def _fused_forward(q, k, v, *, scale: float, block_q: int, block_k: int,
                   interpret: bool) -> jnp.ndarray:
    """Pallas forward on (B, H, N, D): pad, tile, run the flash kernel.

    Tiles directly on the 4D layout (grid (B, H, q_blocks)) — no reshape, no
    nested jit — so the only HBM traffic beyond the block DMAs is the seq/lane
    padding itself, and the walker's bytes proxy sees the kernel at its true
    cost. Not jit-wrapped: callers are already inside jit (train/serve steps)
    or wrap it themselves (bench); interpret mode also runs eagerly.
    """
    b, h, n, d = q.shape
    n_extra = -n % max(block_q, block_k)
    d_extra = -d % LANE
    # lax.pad, not jnp.pad: the jnp wrapper traces as a nested pjit call
    # whose operands the fusion-blind bytes proxy would double-charge
    cfg = ((0, 0, 0), (0, 0, 0), (0, n_extra, 0), (0, d_extra, 0))
    qp, kp, vp = (jax.lax.pad(x, jnp.zeros((), x.dtype), cfg)
                  for x in (q, k, v))
    np_, dp = n + n_extra, d + d_extra
    out = pl.pallas_call(
        functools.partial(_flash_kernel, block_k=block_k, n_valid=n,
                          scale=scale),
        out_shape=jax.ShapeDtypeStruct((b, h, np_, dp), q.dtype),
        grid=(b, h, np_ // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, dp), lambda i, j, l: (i, j, l, 0)),
            pl.BlockSpec((1, 1, np_, dp), lambda i, j, l: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, np_, dp), lambda i, j, l: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, dp), lambda i, j, l: (i, j, l, 0)),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :, :n, :d]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_attention(q, k, v, scale, block_q=BLOCK_Q, block_k=BLOCK_K,
                    interpret=False):
    """Flash attention with a trainable VJP.

    Forward is the Pallas kernel (no (N, N) HBM intermediate).  Backward
    differentiates the mathematically-identical naive formulation — the flash
    backward kernel is future work (docs/ATTENTION.md), so training pays the
    naive backward bytes while serving stays fused.
    """
    return _fused_forward(q, k, v, scale=scale, block_q=block_q,
                          block_k=block_k, interpret=interpret)


def _fused_fwd(q, k, v, scale, block_q, block_k, interpret):
    out = _fused_forward(q, k, v, scale=scale, block_q=block_q,
                         block_k=block_k, interpret=interpret)
    return out, (q, k, v)


def _fused_bwd(scale, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: naive_attention(q_, k_, v_, scale=scale), q, k, v)
    return vjp(g)


fused_attention.defvjp(_fused_fwd, _fused_bwd)


def resolve_impl(impl: str = "auto") -> str:
    """Resolve "auto" to a concrete implementation for this backend.

    TPU → "fused" (unless `DEEPVISION_NO_PALLAS=1`, the same escape hatch as
    `best_iou_auto`); everything else → "naive".  "interpret" forces the
    kernel under the Pallas interpreter on any backend (tests/preflight).
    """
    if impl != "auto":
        return impl
    if (jax.default_backend() == "tpu"
            and os.environ.get("DEEPVISION_NO_PALLAS") != "1"):
        return "fused"
    return "naive"


def attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
              impl: str = "auto", scale: Optional[float] = None,
              block_q: int = BLOCK_Q, block_k: int = BLOCK_K) -> jnp.ndarray:
    """Multi-head attention on (B, H, N, D): softmax(QK^T·scale) @ V.

    impl: "auto" | "naive" | "fused" | "interpret".  "fused" lowers the Pallas
    kernel for the real TPU backend; "interpret" runs the identical kernel
    under the interpreter (the CPU correctness path).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    impl = resolve_impl(impl)
    if impl == "naive":
        return naive_attention(q, k, v, scale=scale)
    if impl in ("fused", "interpret"):
        return fused_attention(q, k, v, scale, block_q, block_k,
                               impl == "interpret")
    raise ValueError(f"unknown attention impl {impl!r}")
