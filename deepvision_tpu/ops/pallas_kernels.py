"""Pallas TPU kernels for the detection hot spots.

The YOLO ignore mask is the reference's memory hot spot: a broadcast IoU between
every prediction and every (padded) ground-truth box, then a max over GT
(`YOLO/tensorflow/yolov3.py:436-470` — a (507·B, 100) intermediate per scale at
13×13 and a (8112·B, 100) one at 52×52). XLA materializes the (B, N, M) IoU
tensor in HBM before reducing it; the kernel below fuses compute + reduction so
only (BLOCK_N, M) tiles ever exist, in VMEM.

Layout choices (see /opt/skills/guides/pallas_guide.md):
- predictions tile the sublane axis in BLOCK_N rows; each coordinate column
  broadcast as (BLOCK_N, 1);
- ground truth is passed TRANSPOSED as (B, 4, M) so each coordinate row is a
  natural (1, M) lane vector, M padded to a multiple of 128 lanes;
- the (BLOCK_N, M) IoU tile lives only in registers/VMEM; the max over lanes
  writes a (BLOCK_N, 1) sublane vector straight to the output block.

CPU fallback: `interpret=True` runs the same kernel under the Pallas interpreter
(used by tests); callers can also use the pure-jnp path in `ops/boxes.py`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANE = 128


def _best_iou_kernel(pred_ref, gt_ref, out_ref):
    """One (BLOCK_N, M) tile: IoU of BLOCK_N pred boxes vs all M GT, max over M.

    pred_ref: (1, BLOCK_N, 4) corner boxes; gt_ref: (1, 4, M) transposed corner
    boxes (padded GT rows are all-zero → zero area → IoU 0); out_ref:
    (1, BLOCK_N, 1).
    """
    pred = pred_ref[0]  # (BLOCK_N, 4)
    gt = gt_ref[0]      # (4, M)

    px1, py1 = pred[:, 0:1], pred[:, 1:2]          # (BLOCK_N, 1)
    px2, py2 = pred[:, 2:3], pred[:, 3:4]
    gx1, gy1 = gt[0:1, :], gt[1:2, :]              # (1, M)
    gx2, gy2 = gt[2:3, :], gt[3:4, :]

    left = jnp.maximum(px1, gx1)                   # (BLOCK_N, M)
    top = jnp.maximum(py1, gy1)
    right = jnp.minimum(px2, gx2)
    bot = jnp.minimum(py2, gy2)
    # overlap clipped to [0, 1] — normalized coords (`utils.py:31-77`)
    iw = jnp.clip(right - left, 0.0, 1.0)
    ih = jnp.clip(bot - top, 0.0, 1.0)
    inter = iw * ih
    area_p = (px2 - px1) * (py2 - py1)
    area_g = (gx2 - gx1) * (gy2 - gy1)
    iou = inter / (area_p + area_g - inter + 1e-7)
    out_ref[0] = jnp.max(iou, axis=1, keepdims=True)  # (BLOCK_N, 1)


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def best_iou(pred_boxes: jnp.ndarray, gt_boxes: jnp.ndarray, *,
             block_n: int = 512, interpret: bool = False) -> jnp.ndarray:
    """max_m IoU(pred_n, gt_m): (B, N, 4) x (B, M, 4) corner boxes → (B, N).

    Fused replacement for `jnp.max(broadcast_iou(pred, gt), -1)` — numerically
    identical (same clipping and epsilon), without the (B, N, M) HBM
    intermediate. Invalid/padded GT rows must be zeroed by the caller (zero area
    → IoU 0, exactly like the jnp path).
    """
    b, n, _ = pred_boxes.shape
    m = gt_boxes.shape[1]
    block_n = min(block_n, n)

    # pad N to the block size and M to full lanes; padded GT columns are zeros
    n_pad = -n % block_n
    m_pad = -m % LANE
    pred = jnp.pad(pred_boxes.astype(jnp.float32), ((0, 0), (0, n_pad), (0, 0)))
    gt_t = jnp.pad(gt_boxes.astype(jnp.float32).transpose(0, 2, 1),
                   ((0, 0), (0, 0), (0, m_pad)))

    grid = (b, (n + n_pad) // block_n)
    out = pl.pallas_call(
        _best_iou_kernel,
        out_shape=jax.ShapeDtypeStruct((b, n + n_pad, 1), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_n, 4), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 4, m + m_pad), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_n, 1), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(pred, gt_t)
    return out[:, :n, 0]


def best_iou_auto(pred_boxes: jnp.ndarray, gt_boxes: jnp.ndarray) -> jnp.ndarray:
    """Dispatch: Pallas kernel on TPU, pure-jnp elsewhere (CPU tests/bench).

    The jnp fallback keeps the op differentiable-by-XLA and portable; the TPU
    path is wrapped in stop_gradient by its caller (the ignore mask is consumed
    through a comparison, so its gradient is identically zero either way).
    `DEEPVISION_NO_PALLAS=1` forces the jnp path (escape hatch if a Mosaic
    lowering regression ever hits a TPU runtime we haven't tested).
    """
    import os
    if (jax.default_backend() == "tpu"
            and os.environ.get("DEEPVISION_NO_PALLAS") != "1"):
        return best_iou(pred_boxes, gt_boxes)
    from .boxes import broadcast_iou
    return jnp.max(broadcast_iou(pred_boxes, gt_boxes), axis=-1)
