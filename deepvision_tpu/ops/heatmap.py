"""Keypoint → 2D-gaussian heatmap rendering, vectorized jnp.

Parity target: `Hourglass/tensorflow/preprocess.py:91-173` — a σ=1 gaussian patch
of amplitude `scale`=12 centered on each (rounded) keypoint, truncated at 3σ,
all-zero when the keypoint is invisible (v==0) or its patch falls fully outside
the heatmap ("a ground truth heatmap of all zeros is provided", Newell §3).

The reference renders each patch with a nested autograph loop + TensorArray
scatter per keypoint (`preprocess.py:143-149`); here the whole (H, W, K) tensor is
one broadcasted expression, so it runs inside the jitted train step on device.
Two reference quirks deliberately NOT replicated (both pinned against the
reference implementation in tests/test_hourglass.py):
1. its patch loop drops the right-most row/column of each 7×7 patch
   (`range(patch_min, patch_max)` with an exclusive bound, `:143-144`); we
   render the full symmetric patch;
2. for patches clipped at the top/left edge it scatters at `heatmap_min + j`
   where j already starts at patch_min (`:145-147`), double-shifting the
   gaussian away from the keypoint (a (0,0) keypoint peaks at (3,3)); we
   center the gaussian on the keypoint as the paper describes.
"""

from __future__ import annotations

import jax.numpy as jnp


def render_gaussian_heatmaps(kp_x: jnp.ndarray, kp_y: jnp.ndarray,
                             visibility: jnp.ndarray, height: int, width: int,
                             sigma: float = 1.0,
                             scale: float = 12.0) -> jnp.ndarray:
    """Render K keypoints into an (height, width, K) heatmap tensor.

    kp_x, kp_y: (K,) keypoint coordinates normalized to [0, 1] (values < 0 mark
    missing joints, as written by the MPII converter,
    `Datasets/MPII/tfrecords_mpii.py:54-60`); visibility: (K,) 0 = invisible.
    """
    x0 = jnp.round(kp_x * width).astype(jnp.int32)    # (K,)
    y0 = jnp.round(kp_y * height).astype(jnp.int32)

    xs = jnp.arange(width, dtype=jnp.int32)[None, :, None]    # (1, W, 1)
    ys = jnp.arange(height, dtype=jnp.int32)[:, None, None]   # (H, 1, 1)
    dx = xs - x0[None, None, :]                               # (H→1, W, K) bcast
    dy = ys - y0[None, None, :]

    r = int(3 * sigma)
    in_patch = (jnp.abs(dx) <= r) & (jnp.abs(dy) <= r)
    gauss = jnp.exp(-(dx.astype(jnp.float32) ** 2 + dy.astype(jnp.float32) ** 2)
                    / (2.0 * sigma * sigma)) * scale

    visible = (visibility > 0) & (kp_x >= 0) & (kp_y >= 0)
    # fully-out-of-bounds patch → all zeros (`preprocess.py:109-110`)
    on_map = ((x0 - r < width) & (y0 - r < height) &
              (x0 + r >= 0) & (y0 + r >= 0))
    keep = (visible & on_map)[None, None, :]
    return jnp.where(in_patch & keep, gauss, 0.0)


def decode_keypoints(heatmaps: jnp.ndarray):
    """Per-joint argmax decode: (..., H, W, K) heatmaps → normalized keypoints.

    Returns (kp_x, kp_y, confidence), each (..., K): the peak location scaled to
    [0, 1] (cell centers) and the peak amplitude. This is the inference decode
    the reference's demo notebook does with numpy argmax over model output
    (`Hourglass/tensorflow/demo_hourglass_pose.ipynb` role).
    """
    h, w, k = heatmaps.shape[-3], heatmaps.shape[-2], heatmaps.shape[-1]
    flat = heatmaps.reshape(*heatmaps.shape[:-3], h * w, k)
    idx = jnp.argmax(flat, axis=-2)                      # (..., K)
    conf = jnp.max(flat, axis=-2)
    kp_y = (idx // w).astype(jnp.float32) / h
    kp_x = (idx % w).astype(jnp.float32) / w
    return kp_x, kp_y, conf
