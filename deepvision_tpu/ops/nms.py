"""Fixed-shape, jit-friendly non-maximum suppression.

Parity target: the reference's greedy multi-label NMS
(`YOLO/tensorflow/postprocess.py:38-99`) — a Python `while` loop over dynamic-size
tensors inside `tf.map_fn`, which cannot compile to XLA. The TPU-native formulation
below is the same greedy algorithm expressed with static shapes: a `lax.fori_loop`
over `max_detection` picks, each iteration selecting the argmax-score survivor and
masking out everything with IoU > threshold. O(D·N) fully-vectorized work instead of
data-dependent control flow; `vmap` supplies the batch dimension.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .boxes import broadcast_iou


def _single_nms(boxes, scores, classes, *, iou_thresh: float, score_thresh: float,
                max_detection: int):
    """Greedy NMS for one image.

    boxes: (N, 4) corner boxes; scores: (N,); classes: (N, C) per-class probs.
    Returns (out_boxes (D,4), out_scores (D,), out_classes (D,C), valid_count ()).
    """
    n = boxes.shape[0]
    num_classes = classes.shape[-1]
    alive = scores >= score_thresh

    out_boxes = jnp.zeros((max_detection, 4), boxes.dtype)
    out_scores = jnp.zeros((max_detection,), scores.dtype)
    out_classes = jnp.zeros((max_detection, num_classes), classes.dtype)
    count = jnp.zeros((), jnp.int32)

    def body(i, carry):
        alive, out_boxes, out_scores, out_classes, count = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        valid = masked[best] > -jnp.inf  # any survivor left?

        out_boxes = out_boxes.at[i].set(jnp.where(valid, boxes[best], 0.0))
        out_scores = out_scores.at[i].set(jnp.where(valid, scores[best], 0.0))
        out_classes = out_classes.at[i].set(jnp.where(valid, classes[best], 0.0))
        count = count + valid.astype(jnp.int32)

        # suppress: the picked box itself + everything overlapping it too much
        # (reference keeps iou <= threshold, postprocess.py:73-74)
        iou = broadcast_iou(boxes[best][None, :], boxes)[0]  # (N,)
        kill = (iou > iou_thresh) | (jnp.arange(n) == best)
        alive = alive & jnp.where(valid, ~kill, True)
        return alive, out_boxes, out_scores, out_classes, count

    _, out_boxes, out_scores, out_classes, count = jax.lax.fori_loop(
        0, max_detection, body, (alive, out_boxes, out_scores, out_classes, count))
    return out_boxes, out_scores, out_classes, count


def batched_nms(boxes, scores, classes, *, iou_thresh: float = 0.5,
                score_thresh: float = 0.5, max_detection: int = 100):
    """Batch greedy NMS (vmapped); same outputs as the reference's
    `batch_non_maximum_suppression` (`YOLO/tensorflow/postprocess.py:38-99`):
    (boxes (B,D,4), scores (B,D), class_probs (B,D,C), valid_counts (B,))."""
    fn = functools.partial(_single_nms, iou_thresh=iou_thresh,
                           score_thresh=score_thresh, max_detection=max_detection)
    return jax.vmap(fn)(boxes, scores, classes)
