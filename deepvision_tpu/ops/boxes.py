"""Box geometry ops shared by the detection families.

Parity targets: `YOLO/tensorflow/utils.py:4-84` (xywh→corners converters, broadcast
IoU, clipped binary cross-entropy). Implemented as pure jnp so they run inside jitted
train steps on TPU; the BCE variant used in losses works on logits
(`optax.sigmoid_binary_cross_entropy`) rather than clipped probabilities for
numerical stability, with identical semantics.
"""

from __future__ import annotations

import jax.numpy as jnp


def xywh_to_x1y1x2y2(box: jnp.ndarray) -> jnp.ndarray:
    """(cx, cy, w, h) → (xmin, ymin, xmax, ymax). Reference
    `YOLO/tensorflow/utils.py:4-12` (its name says x1x2y1y2 but the layout it
    produces is xmin,ymin,xmax,ymax — we name it honestly)."""
    xy = box[..., 0:2]
    wh = box[..., 2:4]
    return jnp.concatenate([xy - wh / 2.0, xy + wh / 2.0], axis=-1)


def xywh_to_y1x1y2x2(box: jnp.ndarray) -> jnp.ndarray:
    """(cx, cy, w, h) → (ymin, xmin, ymax, xmax) — the tf.image convention
    (`YOLO/tensorflow/utils.py:15-28`)."""
    x = box[..., 0:1]
    y = box[..., 1:2]
    w = box[..., 2:3]
    h = box[..., 3:4]
    yx = jnp.concatenate([y, x], axis=-1)
    hw = jnp.concatenate([h, w], axis=-1)
    return jnp.concatenate([yx - hw / 2.0, yx + hw / 2.0], axis=-1)


def x1y1x2y2_to_xywh(box: jnp.ndarray) -> jnp.ndarray:
    """(xmin, ymin, xmax, ymax) → (cx, cy, w, h)."""
    xy = (box[..., 0:2] + box[..., 2:4]) / 2.0
    wh = box[..., 2:4] - box[..., 0:2]
    return jnp.concatenate([xy, wh], axis=-1)


def broadcast_iou(box_a: jnp.ndarray, box_b: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU between (..., N, 4) and (..., M, 4) corner boxes → (..., N, M).

    Reference `YOLO/tensorflow/utils.py:31-77`: normalized coordinates, overlap
    widths clipped to [0, 1], epsilon-guarded union.
    """
    a = box_a[..., :, None, :]  # (..., N, 1, 4)
    b = box_b[..., None, :, :]  # (..., 1, M, 4)
    left = jnp.maximum(a[..., 0], b[..., 0])
    top = jnp.maximum(a[..., 1], b[..., 1])
    right = jnp.minimum(a[..., 2], b[..., 2])
    bot = jnp.minimum(a[..., 3], b[..., 3])
    iw = jnp.clip(right - left, 0.0, 1.0)
    ih = jnp.clip(bot - top, 0.0, 1.0)
    inter = iw * ih
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    union = area_a + area_b - inter
    return inter / (union + 1e-7)


def binary_cross_entropy(probs: jnp.ndarray, labels: jnp.ndarray,
                         epsilon: float = 1e-7) -> jnp.ndarray:
    """Elementwise BCE on probabilities with clipping — exact semantics of
    `YOLO/tensorflow/utils.py:80-84`. Prefer the logits form in losses."""
    p = jnp.clip(probs, epsilon, 1.0 - epsilon)
    return -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
