"""int8 post-training quantization as a jaxpr rewrite.

The serving engine's predict function is an arbitrary composition of the
zoo's model code — thirteen families, none of which carry quantization
hooks. Rather than threading an int8 flag through every Flax module (and
re-auditing every family by hand), this module quantizes at the level
jaxvet already audits: the **closed jaxpr** of the real predict function.

Three stages, mirroring the TensorRT/AQT-style PTQ recipe:

1. `plan_quantization(closed, head_dims)` — purely STRUCTURAL (no FLOPs,
   abstract-safe, the same walk jaxvet's cost model does): find every
   conv_general_dilated / dot_general whose rhs operand is a weight leaf of
   the `variables` pytree (provenance traced through dtype casts), skip the
   deliberate f32 heads (the `head_dims` convention shared with jaxvet's
   DTYPE rule via `core.scoring.serving_head_dims`), and record, per heavy
   equation, the weight leaf index and the per-output-channel axis the
   weight scales will broadcast over.

2. `calibrate(plan, closed, variables, images)` — replay the SAME jaxpr
   concretely on a pinned calibration batch, recording the absolute-max of
   every planned equation's activation input. Per-tensor activation scales
   (`absmax / 127`) are pinned from this one deterministic pass; per-channel
   WEIGHT scales are data-free (absmax over the kernel's non-output dims)
   and recomputed for every weight generation, which is what lets hot
   reload / promotion re-quantize a new checkpoint with zero recompiles.

3. `quantized_predict_fn(plan, closed)` — a callable with the engine's
   exact `(variables, images)` signature that replays the jaxpr with every
   planned equation swapped for its integer twin:

       q_x   = clip(round(x / s_x), -127, 127) -> int8
       acc   = conv/dot(q_x, w_int8, preferred_element_type=int32)
       y     = acc * (s_x * s_w[channel])      -> the original out dtype

   i.e. int8 storage AND int8 MXU compute with int32 accumulation,
   dequantized at the equation boundary — activations between layers (BN,
   residual adds, nonlinearities) keep the model's declared policy, and the
   engine's f32-output contract is untouched. Every other equation replays
   verbatim, so the quantized program IS the original program modulo the
   planned substitutions — which is exactly what jaxvet's QUANT family
   re-audits on the traced quantized jaxpr.

Quantized weights travel as a flat `{"q": {leaf: {"w": int8, "s": f32}},
"f": {leaf: value}}` pytree built by `quantize_variables`, so the compiled
bucket programs take weights as ARGUMENTS (not baked constants): swapping
in a re-quantized generation is the same one-reference flip as bf16 serving
(serve/engine.py), zero recompiles.

Accumulator-range note: int8xint8 into int32 overflows only past ~1.3e5
taps (127^2 * K < 2^31); the zoo's largest contraction (VGG's 25088-wide
fc1) is ~2e4 taps, and `plan_quantization` refuses equations beyond the
bound rather than wrapping silently.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.core import Jaxpr, Literal

HEAVY_PRIMS = ("conv_general_dilated", "dot_general")

# provenance survives pure dtype casts only: a reshaped/transposed kernel
# would scramble the per-channel axis bookkeeping, so it is left unquantized
# (none of the zoo's modules reshape kernels between init and use)
_CAST_PRIMS = frozenset({"convert_element_type"})

# int8 x int8 partial products are <= 127^2; int32 accumulation is exact
# while taps * 127^2 < 2^31 — refuse (leave in float) past this, loudly in
# the plan rather than silently wrapping at dispatch
MAX_ACC_TAPS = (2 ** 31 - 1) // (127 * 127)

QMAX = 127.0


class QuantRefusal(ValueError):
    """PTQ refused the whole program, loudly, with a machine-readable
    `reason` (surfaced on /healthz via the arm-time decision record,
    serve/quantize.arm_int8). Raised instead of returning a plan that would
    silently serve a model whose hot path cannot quantize — the ViT case:
    attention's softmax-adjacent contractions are activation×activation
    (no weight operand, nothing to hold scales for), so if the QKV/out/MLP
    projections cannot be planned either, int8 would be a pure no-op lie."""

    def __init__(self, message: str, reason: str):
        super().__init__(message)
        self.reason = reason


@dataclasses.dataclass(frozen=True)
class QuantEqn:
    """One heavy equation the plan quantizes."""
    eqn_index: int            # position in jaxpr.eqns
    prim: str                 # conv_general_dilated | dot_general
    leaf_index: int           # flat index into the variables pytree
    # per-channel scale layout: weight dims reduced for the scale, and the
    # broadcast shape that lands the scale vector on the OUTPUT's channel
    # dim (per-tensor fallback: w_reduce_axes covers every dim and
    # out_broadcast is all-1s)
    w_reduce_axes: Tuple[int, ...]
    scale_shape: Tuple[int, ...]       # shape of the stored scale array
    out_broadcast: Tuple[int, ...]     # reshape of scale for the dequant mul


@dataclasses.dataclass
class QuantPlan:
    """The structural half of PTQ: which equations quantize and how. Built
    abstractly; `act_scales` stays None until `calibrate` fills it."""
    eqns: List[QuantEqn]
    n_var_leaves: int                  # leaves of the variables pytree
    skipped_head: int = 0              # heavy eqns exempted as f32 heads
    skipped_other: int = 0             # non-weight rhs / unsupported layout
    # softmax-adjacent activation×activation contractions (QK^T, PV): no
    # weight operand exists, so int8 would need calibrated scales on BOTH
    # sides plus an int32 accumulator across the full key depth — skipped BY
    # NAME so /healthz can report a ViT's float attention honestly instead
    # of burying it in skipped_other
    skipped_attention: int = 0
    # attention already fused into a Pallas kernel (pallas_call in the
    # trace): its contractions live in VMEM at the kernel's own precision
    # and are not PTQ targets; counted so the decision record names them
    fused_attention: int = 0
    act_scales: Optional[Dict[int, float]] = None   # eqn_index -> s_x

    @property
    def leaf_indices(self) -> frozenset:
        return frozenset(q.leaf_index for q in self.eqns)

    def summary(self) -> dict:
        return {"quantized": len(self.eqns),
                "skipped_head": self.skipped_head,
                "skipped_other": self.skipped_other,
                "skipped_attention": self.skipped_attention,
                "fused_attention": self.fused_attention,
                "calibrated": self.act_scales is not None}


def _aval(v):
    return getattr(v, "aval", None)


def _conv_channel_layout(eqn) -> Optional[Tuple[Tuple[int, ...],
                                                Tuple[int, ...],
                                                Tuple[int, ...]]]:
    """(w_reduce_axes, scale_shape, out_broadcast) for a conv kernel:
    per-OUTPUT-channel scales (the rhs_spec's O dim), broadcast onto the
    output's feature dim. Grouped/depthwise convs keep the same layout —
    O already enumerates every output channel."""
    dnums = eqn.params["dimension_numbers"]
    rhs_shape = tuple(_aval(eqn.invars[1]).shape)
    out_shape = tuple(_aval(eqn.outvars[0]).shape)
    o_dim = dnums.rhs_spec[0]
    reduce_axes = tuple(i for i in range(len(rhs_shape)) if i != o_dim)
    scale_shape = (rhs_shape[o_dim],)
    bcast = [1] * len(out_shape)
    bcast[dnums.out_spec[1]] = rhs_shape[o_dim]
    return reduce_axes, scale_shape, tuple(bcast)


def _dot_channel_layout(eqn) -> Optional[Tuple[Tuple[int, ...],
                                               Tuple[int, ...],
                                               Tuple[int, ...]]]:
    """Per-channel layout for a dot_general rhs (the Dense case: rhs
    (in, out), one free dim that is the LAST output dim). Anything fancier
    (batched dots, multi-free-dim rhs) falls back to one per-tensor scale —
    correct, just coarser."""
    (_, rhs_c), (_, rhs_b) = eqn.params["dimension_numbers"]
    rhs_shape = tuple(_aval(eqn.invars[1]).shape)
    out_shape = tuple(_aval(eqn.outvars[0]).shape)
    free = [i for i in range(len(rhs_shape))
            if i not in rhs_c and i not in rhs_b]
    if len(free) == 1 and not rhs_b \
            and out_shape and out_shape[-1] == rhs_shape[free[0]]:
        reduce_axes = tuple(i for i in range(len(rhs_shape))
                            if i != free[0])
        bcast = [1] * len(out_shape)
        bcast[-1] = rhs_shape[free[0]]
        return reduce_axes, (rhs_shape[free[0]],), tuple(bcast)
    # per-tensor fallback
    return (tuple(range(len(rhs_shape))), (), tuple([1] * len(out_shape)))


def _contraction_taps(eqn) -> int:
    """Accumulation depth of one output element — the int32-overflow bound."""
    if eqn.primitive.name == "conv_general_dilated":
        dnums = eqn.params["dimension_numbers"]
        rhs = tuple(_aval(eqn.invars[1]).shape)
        spatial = [rhs[d] for d in dnums.rhs_spec[2:]]
        return int(math.prod(spatial)) * int(rhs[dnums.rhs_spec[1]])
    (lhs_c, _), _ = eqn.params["dimension_numbers"]
    lhs = tuple(_aval(eqn.invars[0]).shape)
    return int(math.prod(lhs[d] for d in lhs_c)) if lhs_c else 1


def _eqn_dims(eqn) -> set:
    dims = set()
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = _aval(v)
        if aval is not None and hasattr(aval, "shape"):
            dims.update(int(d) for d in aval.shape)
    return dims


def _contains_pallas(eqn) -> bool:
    """True when a pallas_call hides anywhere under this equation's params
    (the fused-attention custom_vjp wrapper is the zoo's only producer)."""
    if eqn.primitive.name == "pallas_call":
        return True
    stack = [v for v in eqn.params.values()]
    while stack:
        item = stack.pop()
        if isinstance(item, (list, tuple)):
            stack.extend(item)
            continue
        inner = item.jaxpr if hasattr(item, "jaxpr") else item
        if isinstance(inner, Jaxpr):
            if any(e.primitive.name == "pallas_call" or _contains_pallas(e)
                   for e in inner.eqns):
                return True
    return False


def plan_quantization(closed, head_dims=frozenset()) -> QuantPlan:
    """Structural quantization plan over a predict jaxpr traced as
    `predict(variables, images)`. Abstract-safe: only shapes/dtypes and the
    equation graph are consulted (jaxvet builds plans on ShapeDtypeStruct
    traces). `head_dims` marks the deliberate f32 heads (class/box/keypoint
    widths) that stay in float — the same convention jaxvet's DTYPE rule
    applies."""
    jaxpr: Jaxpr = closed.jaxpr
    n_leaves = len(jaxpr.invars) - 1   # last invar is the images batch
    # provenance: var -> variables leaf index, through dtype casts only
    prov: Dict[Any, int] = {v: i for i, v in enumerate(jaxpr.invars[:-1])}
    plan_eqns: List[QuantEqn] = []
    skipped_head = skipped_other = skipped_attention = fused_attention = 0
    for idx, eqn in enumerate(jaxpr.eqns):
        name = eqn.primitive.name
        if name in _CAST_PRIMS and not isinstance(eqn.invars[0], Literal):
            src = eqn.invars[0]
            if src in prov and jnp.issubdtype(
                    _aval(eqn.outvars[0]).dtype, jnp.floating):
                prov[eqn.outvars[0]] = prov[src]
            continue
        if name not in HEAVY_PRIMS:
            if name.startswith(("custom_vjp_call", "custom_jvp_call")) \
                    and _contains_pallas(eqn):
                fused_attention += 1
            continue
        lhs, rhs = eqn.invars[0], eqn.invars[1]
        lhs_aval, rhs_aval = _aval(lhs), _aval(rhs)
        if (isinstance(rhs, Literal) or rhs not in prov
                or not jnp.issubdtype(lhs_aval.dtype, jnp.floating)
                or not jnp.issubdtype(rhs_aval.dtype, jnp.floating)):
            # activation×activation float contraction with no weight operand
            # on either side: the attention shape (QK^T, PV). Named so the
            # serve decision record can say "attention stays float" instead
            # of hiding it — and past the int32-accumulator bound these
            # could not quantize even with dual activation scales.
            if (not isinstance(rhs, Literal) and rhs not in prov
                    and lhs not in prov
                    and jnp.issubdtype(lhs_aval.dtype, jnp.floating)
                    and jnp.issubdtype(rhs_aval.dtype, jnp.floating)):
                skipped_attention += 1
            else:
                skipped_other += 1
            continue
        if head_dims & _eqn_dims(eqn):
            skipped_head += 1          # deliberate f32 head: stays float
            continue
        if _contraction_taps(eqn) > MAX_ACC_TAPS:
            skipped_other += 1         # int32 accumulator could overflow
            continue
        if name == "conv_general_dilated":
            layout = _conv_channel_layout(eqn)
        else:
            layout = _dot_channel_layout(eqn)
        reduce_axes, scale_shape, out_bcast = layout
        plan_eqns.append(QuantEqn(
            eqn_index=idx, prim=name, leaf_index=prov[rhs],
            w_reduce_axes=reduce_axes, scale_shape=scale_shape,
            out_broadcast=out_bcast))
    if (skipped_attention or fused_attention) and not plan_eqns:
        # a transformer whose projections could not be planned: int8 would
        # quantize NOTHING while the name promises a byte cut — refuse, by
        # name, rather than serve the lie (arm_int8 turns this into a
        # refusal decision record on /healthz)
        raise QuantRefusal(
            f"attention program has {skipped_attention} float "
            f"activation×activation contraction(s) and "
            f"{fused_attention} fused kernel call(s) but ZERO quantizable "
            f"projection weights — int8 serving would be a no-op; refusing "
            f"rather than silently serving a half-quantized model",
            reason="attention_projections_unquantizable")
    return QuantPlan(eqns=plan_eqns, n_var_leaves=n_leaves,
                     skipped_head=skipped_head, skipped_other=skipped_other,
                     skipped_attention=skipped_attention,
                     fused_attention=fused_attention)


# -- jaxpr replay -------------------------------------------------------------

# call-style primitives whose bind() signature is not (invals, **params):
# inline-evaluate their inner jaxpr with default semantics instead. Heavy
# ops nested inside them are NOT quantized (the plan walks the top level
# only) — the serving predicts trace flat, so nothing hides there; a relu's
# custom_jvp body is elementwise anyway.
_CALL_PRIMS = frozenset({"custom_jvp_call", "custom_vjp_call", "pjit",
                         "closed_call", "core_call", "remat", "checkpoint"})


def _default_bind(eqn, invals):
    """Replay one equation with its original semantics."""
    if eqn.primitive.name in _CALL_PRIMS:
        inner = (eqn.params.get("call_jaxpr") or eqn.params.get("jaxpr")
                 or eqn.params.get("fun_jaxpr"))
        if inner is not None:
            closed = inner if hasattr(inner, "jaxpr") else None
            if closed is not None:
                return jax.core.eval_jaxpr(closed.jaxpr, closed.consts,
                                           *invals)
            return jax.core.eval_jaxpr(inner, [], *invals)
    out = eqn.primitive.bind(*invals, **eqn.params)
    return out if eqn.primitive.multiple_results else [out]


def _replay(jaxpr: Jaxpr, consts, args, handler):
    """Minimal closed-jaxpr interpreter: every equation binds verbatim
    except where `handler(idx, eqn, invals)` returns a substitute result
    list (NotImplemented = default semantics)."""
    env: Dict[Any, Any] = {}

    def read(v):
        return v.val if isinstance(v, Literal) else env[v]

    for v, c in zip(jaxpr.constvars, consts):
        env[v] = c
    for v, a in zip(jaxpr.invars, args):
        env[v] = a
    for idx, eqn in enumerate(jaxpr.eqns):
        invals = [read(v) for v in eqn.invars]
        out = handler(idx, eqn, invals)
        if out is NotImplemented:
            out = _default_bind(eqn, invals)
        for v, val in zip(eqn.outvars, out):
            env[v] = val
    return [read(v) for v in jaxpr.outvars]


def calibrate(plan: QuantPlan, closed, variables, images) -> QuantPlan:
    """Fill the plan's per-tensor activation scales by replaying the f32
    jaxpr on ONE pinned calibration batch and recording each planned
    equation's activation abs-max. Deterministic per (jaxpr, batch); the
    resulting scales are python floats — closure constants of the compiled
    int8 programs, identical for every weight generation."""
    flat_vars = jax.tree_util.tree_leaves(variables)
    if len(flat_vars) != plan.n_var_leaves:
        raise ValueError(
            f"calibration variables have {len(flat_vars)} leaves; the plan "
            f"was built over {plan.n_var_leaves}")
    args = [jnp.asarray(v) for v in flat_vars] + [jnp.asarray(images)]
    wanted = {q.eqn_index for q in plan.eqns}
    absmax: Dict[int, float] = {}

    def handler(idx, eqn, invals):
        if idx in wanted:
            absmax[idx] = float(jnp.max(jnp.abs(
                invals[0].astype(jnp.float32))))
        return NotImplemented

    _replay(closed.jaxpr, closed.consts, args, handler)
    plan.act_scales = {
        # a degenerate all-zero calibration activation still needs a
        # nonzero scale (divide-by-zero guard); 1/127 maps 0 -> 0 exactly
        idx: (m / QMAX if m > 0.0 else 1.0 / QMAX)
        for idx, m in absmax.items()}
    return plan


# -- weights ------------------------------------------------------------------

def quantize_variables(plan: QuantPlan, variables):
    """Per-channel symmetric int8 quantization of the plan's weight leaves.
    Returns the flat quantized pytree the int8 bucket programs take as
    their `variables` argument:

        {"q": {"<leaf>": {"w": int8 kernel, "s": f32 scales}},
         "f": {"<leaf>": untouched leaf}}

    Data-free (absmax over the kernel itself), so a NEW weight generation
    re-quantizes under the pinned activation scales without touching the
    compiled programs — shapes/dtypes (the engine's compatibility
    signature) depend only on the plan."""
    flat, _ = jax.tree_util.tree_flatten(variables)
    if len(flat) != plan.n_var_leaves:
        raise ValueError(
            f"variables have {len(flat)} leaves; the plan was built over "
            f"{plan.n_var_leaves}")
    by_leaf = {q.leaf_index: q for q in plan.eqns}
    q: Dict[str, dict] = {}
    f: Dict[str, Any] = {}
    for i, leaf in enumerate(flat):
        spec = by_leaf.get(i)
        if spec is None:
            f[str(i)] = leaf
            continue
        w = jnp.asarray(leaf, jnp.float32)
        absmax = jnp.max(jnp.abs(w), axis=spec.w_reduce_axes)
        scale = jnp.where(absmax > 0, absmax / QMAX, 1.0 / QMAX)
        scale_b = jnp.expand_dims(scale, spec.w_reduce_axes) \
            if spec.scale_shape else scale
        wq = jnp.clip(jnp.round(w / scale_b), -QMAX, QMAX).astype(jnp.int8)
        q[str(i)] = {"w": wq, "s": scale.astype(jnp.float32)}
    return {"q": q, "f": f}


def quantized_weight_specs(plan: QuantPlan, var_specs: List[Any]):
    """The abstract twin of `quantize_variables`: ShapeDtypeStructs of the
    quantized pytree from the f32 leaf specs — what jaxvet traces the int8
    unit with, and what `weight_signature` compatibility is checked
    against."""
    S = jax.ShapeDtypeStruct
    by_leaf = {q.leaf_index: q for q in plan.eqns}
    q: Dict[str, dict] = {}
    f: Dict[str, Any] = {}
    for i, spec in enumerate(var_specs):
        qe = by_leaf.get(i)
        if qe is None:
            f[str(i)] = S(tuple(spec.shape), spec.dtype)
        else:
            q[str(i)] = {"w": S(tuple(spec.shape), jnp.int8),
                         "s": S(qe.scale_shape, jnp.float32)}
    return {"q": q, "f": f}


def tree_nbytes(tree) -> int:
    """Total payload bytes of a pytree of arrays (the bytes/batch weight
    term the int8 bench reports)."""
    return int(sum(np.prod(np.shape(leaf))
                   * jnp.dtype(getattr(leaf, "dtype", np.float32)).itemsize
                   for leaf in jax.tree_util.tree_leaves(tree)))


# -- the quantized predict ----------------------------------------------------

def quantized_predict_fn(plan: QuantPlan, closed, out_tree=None):
    """Build `qpredict(qvariables, images)` — the int8 twin of the predict
    the jaxpr was traced from. Replays every equation verbatim except:

    - planned heavy equations run int8 x int8 -> int32 and dequantize at
      the boundary back to the equation's ORIGINAL output dtype;
    - the dtype-cast feeding a quantized weight is dropped (the int8 kernel
      is consumed directly).

    Traceable (jit/AOT-lower) like any jax function; activation scales are
    baked closure floats, weights arrive as arguments."""
    if plan.act_scales is None:
        raise ValueError("plan is not calibrated — run calibrate() (or "
                         "inject unit scales for an abstract trace) first")
    jaxpr: Jaxpr = closed.jaxpr
    consts = closed.consts
    by_eqn = {q.eqn_index: q for q in plan.eqns}
    # vars whose value IS a quantized weight (the leaf invar and its cast
    # descendants): replay substitutes the QTensor pair for them
    qleaves = plan.leaf_indices

    expand_axes = {q.leaf_index: q.w_reduce_axes for q in plan.eqns}

    def qpredict(qvariables, images):
        qmap, fmap = qvariables["q"], qvariables["f"]
        args: List[Any] = []
        for i in range(plan.n_var_leaves):
            if i in qleaves:
                args.append(_QW(qmap[str(i)]["w"], qmap[str(i)]["s"],
                                expand_axes[i]))
            else:
                args.append(fmap[str(i)])
        args.append(images)

        def handler(idx, eqn, invals):
            spec = by_eqn.get(idx)
            if spec is not None:
                x, w = invals[0], invals[1]
                if not isinstance(w, _QW):   # plan/weights drifted apart
                    raise ValueError(
                        f"eqn {idx} ({eqn.primitive.name}) expected a "
                        f"quantized weight — qvariables do not match the "
                        f"plan")
                s_x = plan.act_scales[idx]
                out_dtype = _aval(eqn.outvars[0]).dtype
                qx = jnp.clip(jnp.round(x.astype(jnp.float32) * (1.0 / s_x)),
                              -QMAX, QMAX).astype(jnp.int8)
                params = dict(eqn.params,
                              preferred_element_type=jnp.dtype(jnp.int32))
                acc = eqn.primitive.bind(qx, w.w, *invals[2:], **params)
                scale = (w.s.reshape(spec.out_broadcast)
                         if spec.scale_shape else w.s)
                return [(acc.astype(jnp.float32) * (scale * s_x))
                        .astype(out_dtype)]
            # a float cast of a quantized weight: absorbed (the int8 kernel
            # feeds its conv directly; any OTHER use dequantizes here)
            if any(isinstance(v, _QW) for v in invals):
                if eqn.primitive.name in _CAST_PRIMS \
                        and isinstance(invals[0], _QW):
                    return [invals[0]]
                return _default_bind(eqn, [v.dequant() if isinstance(v, _QW)
                                           else v for v in invals])
            return NotImplemented

        out = _replay(jaxpr, consts, args, handler)
        if out_tree is not None:
            return jax.tree_util.tree_unflatten(out_tree, out)
        # no out_tree recorded: single-output predicts unwrap, multi-output
        # predicts come back as the flat tuple (leaf order preserved)
        return out[0] if len(out) == 1 else tuple(out)

    return qpredict


class _QW:
    """Replay-time sentinel carrying an int8 kernel + its per-channel
    scales (reduced over `axes`) through the cast chain to its conv/dot."""

    __slots__ = ("w", "s", "axes")

    def __init__(self, w, s, axes):
        self.w = w
        self.s = s
        self.axes = axes

    def dequant(self):
        scale = self.s
        if np.ndim(scale):              # re-expand the reduced axes
            scale = jnp.expand_dims(scale, self.axes)
        return self.w.astype(jnp.float32) * scale
