"""YOLO V3 box coding, on-device label encoding, and loss — pure jnp.

Parity targets (all under `/root/reference/YOLO/tensorflow/`):
- `get_absolute_yolo_box` / `get_relative_yolo_box` (`yolov3.py:238-349`): the
  (tx,ty,tw,th) ↔ (bx,by,bw,bh) transforms with meshgrid cell offsets.
- `Preprocessor.preprocess_label_for_one_scale` + `find_best_anchor`
  (`preprocess.py:137-269`): ground-truth assignment to grid cells.
- `YoloLoss` (`yolov3.py:352-563`): xy/wh/class/obj losses with small-box weighting
  and the IoU ignore mask.

TPU-first design notes:
- Label encoding runs ON DEVICE inside the jitted train step, vectorized over a
  fixed `MAX_BOXES` ground-truth pad. The reference encodes labels on the host with
  an autograph `tf.range` loop + TensorArray per example (`preprocess.py:169-223`);
  here the same assignment is one masked scatter (`.at[...].set(mode='drop')`) —
  static shapes, no per-example Python, nothing for the host to bottleneck on.
- The ignore mask takes IoU against the padded ground-truth list directly (the
  reference reconstructs at most 100 boxes from the dense label by sorting,
  `yolov3.py:448-454` — same cap, same semantics, minus the reconstruction).
- BCE terms are computed from logits (`optax.sigmoid_binary_cross_entropy`) instead
  of clipped probabilities (`utils.py:80-84`) for numerical stability.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .boxes import xywh_to_x1y1x2y2

# The 9 COCO anchors, normalized by the 416 training resolution
# (`yolov3.py:18-20`). Groups of 3 per scale: [0:3]→stride 8, [3:6]→16, [6:9]→32.
ANCHORS_WH = np.array([[10, 13], [16, 30], [33, 23], [30, 61], [62, 45],
                       [59, 119], [116, 90], [156, 198], [373, 326]],
                      np.float32) / 416.0

MAX_BOXES = 100  # ground-truth pad; the reference caps its ignore mask at 100 too

LAMBDA_COORD = 5.0   # YoloV1 eq. 3 weights (`yolov3.py:357-358`)
LAMBDA_NOOBJ = 0.5
IGNORE_THRESH = 0.5  # `yolov3.py:355`


def _cell_offsets(grid_size: int) -> jnp.ndarray:
    """(g, g, 1, 2) tensor of (Cx, Cy) cell offsets — row y, column x, so
    offsets[y, x] == (x, y). Matches the meshgrid walkthrough `yolov3.py:261-292`."""
    cx, cy = jnp.meshgrid(jnp.arange(grid_size), jnp.arange(grid_size))
    return jnp.stack([cx, cy], axis=-1)[:, :, None, :].astype(jnp.float32)


def decode_boxes(y_pred: jnp.ndarray, anchors_wh, num_classes: int):
    """Raw head output → absolute normalized boxes (`get_absolute_yolo_box`,
    `yolov3.py:238-326`).

    y_pred: (..., g, g, 3, 5 + C) raw logits.
    Returns (box_xywh (...,g,g,3,4), objectness (...,g,g,3,1), classes (...,g,g,3,C)),
    with objectness/classes sigmoided.
    """
    t_xy = y_pred[..., 0:2]
    t_wh = y_pred[..., 2:4]
    objectness = jax.nn.sigmoid(y_pred[..., 4:5])
    classes = jax.nn.sigmoid(y_pred[..., 5:5 + num_classes])

    grid_size = y_pred.shape[-4]
    c_xy = _cell_offsets(grid_size)
    # bx = sigmoid(tx) + Cx, normalized by grid size; bw = exp(tw) * pw
    b_xy = (jax.nn.sigmoid(t_xy) + c_xy) / float(grid_size)
    b_wh = jnp.exp(t_wh) * jnp.asarray(anchors_wh, y_pred.dtype)
    return jnp.concatenate([b_xy, b_wh], axis=-1), objectness, classes


def encode_boxes(y_true_xywh: jnp.ndarray, anchors_wh) -> jnp.ndarray:
    """Absolute normalized (bx,by,bw,bh) → cell-relative (tx,ty,tw,th) — the inverse
    transform (`get_relative_yolo_box`, `yolov3.py:329-349`), with the same
    zero-guard for empty cells (log of 0/anchor → 0)."""
    grid_size = y_true_xywh.shape[-4]
    c_xy = _cell_offsets(grid_size)
    b_xy = y_true_xywh[..., 0:2]
    b_wh = y_true_xywh[..., 2:4]
    t_xy = b_xy * float(grid_size) - c_xy
    raw = b_wh / jnp.asarray(anchors_wh, y_true_xywh.dtype)
    t_wh = jnp.where(raw > 0, jnp.log(jnp.maximum(raw, 1e-12)), 0.0)
    return jnp.concatenate([t_xy, t_wh], axis=-1)


def find_best_anchor(boxes_x1y1x2y2: jnp.ndarray,
                     anchors_wh=None) -> jnp.ndarray:
    """Best of the 9 anchors per ground-truth box by centered-IoU
    (`Preprocessor.find_best_anchor`, `preprocess.py:226-269`).

    boxes: (N, 4) corner boxes → (N,) int32 anchor indices in [0, 9).
    """
    anchors = jnp.asarray(ANCHORS_WH if anchors_wh is None else anchors_wh)
    box_wh = boxes_x1y1x2y2[..., 2:4] - boxes_x1y1x2y2[..., 0:2]  # (N, 2)
    inter = (jnp.minimum(box_wh[..., None, 0], anchors[..., 0]) *
             jnp.minimum(box_wh[..., None, 1], anchors[..., 1]))  # (N, 9)
    box_area = box_wh[..., 0] * box_wh[..., 1]
    anchor_area = anchors[..., 0] * anchors[..., 1]
    iou = inter / (box_area[..., None] + anchor_area - inter + 1e-12)
    return jnp.argmax(iou, axis=-1).astype(jnp.int32)


def encode_labels_one_scale(classes_onehot: jnp.ndarray, boxes: jnp.ndarray,
                            valid: jnp.ndarray, grid_size: int,
                            scale_index: int, anchors_wh=None) -> jnp.ndarray:
    """Dense (g, g, 3, 5+C) target for one scale from padded ground truth —
    the vectorized equivalent of `preprocess_label_for_one_scale`
    (`preprocess.py:137-224`).

    classes_onehot: (N, C); boxes: (N, 4) corner boxes; valid: (N,) bool/0-1 mask.
    A box contributes iff it is valid AND its best anchor belongs to this scale
    (anchors 3*scale_index .. 3*scale_index+2). grid[y][x][anchor] layout.
    """
    num_classes = classes_onehot.shape[-1]
    anchor_idx = find_best_anchor(boxes, anchors_wh)        # (N,)
    in_scale = (anchor_idx // 3) == scale_index
    ok = valid.astype(bool) & in_scale
    adjusted_anchor = anchor_idx % 3

    box_xy = (boxes[..., 0:2] + boxes[..., 2:4]) / 2.0
    box_wh = boxes[..., 2:4] - boxes[..., 0:2]
    cell = jnp.floor(box_xy * grid_size).astype(jnp.int32)  # (N, 2) = (gx, gy)

    updates = jnp.concatenate(
        [box_xy, box_wh, jnp.ones_like(box_xy[..., :1]),
         classes_onehot.astype(jnp.float32)], axis=-1)      # (N, 5+C)

    # Scatter with dropped-out-of-range indices: boxes not in this scale get index
    # `grid_size` (out of bounds → dropped by mode='drop').
    oob = jnp.int32(grid_size)
    gy = jnp.where(ok, cell[..., 1], oob)
    gx = jnp.where(ok, cell[..., 0], oob)
    y = jnp.zeros((grid_size, grid_size, 3, 5 + num_classes), jnp.float32)
    return y.at[gy, gx, adjusted_anchor].set(updates, mode="drop")


def encode_labels(classes_onehot, boxes, valid, grid_sizes: Sequence[int],
                  anchors_wh=None) -> Tuple[jnp.ndarray, ...]:
    """Per-scale dense labels for a BATCH of padded ground truth (vmapped scatter).

    classes_onehot: (B, N, C); boxes: (B, N, 4); valid: (B, N).
    grid_sizes ordered like the model outputs: finest (stride 8) first
    (reference label tuple, `preprocess.py:27-34`).
    """
    out = []
    for scale_index, g in enumerate(grid_sizes):
        fn = lambda c, b, v: encode_labels_one_scale(  # noqa: E731
            c, b, v, g, scale_index, anchors_wh)
        out.append(jax.vmap(fn)(classes_onehot, boxes, valid))
    return tuple(out)


def yolo_loss_one_scale(y_true: jnp.ndarray, y_pred: jnp.ndarray,
                        gt_boxes: jnp.ndarray, gt_valid: jnp.ndarray,
                        scale_anchors_wh, num_classes: int) -> dict:
    """Per-example YOLO loss for one scale (`YoloLoss.__call__`, `yolov3.py:360-434`).

    y_true: (B, g, g, 3, 5+C) dense targets (absolute xywh + obj + one-hot).
    y_pred: (B, g, g, 3, 5+C) raw head logits.
    gt_boxes: (B, N, 4) corner ground truth (for the ignore mask); gt_valid: (B, N).
    Returns dict of (B,) loss components: xy, wh, class, obj, total.
    """
    anchors = jnp.asarray(scale_anchors_wh, jnp.float32)
    y_pred = y_pred.astype(jnp.float32)
    y_true = y_true.astype(jnp.float32)

    pred_xy_rel = jax.nn.sigmoid(y_pred[..., 0:2])
    pred_wh_rel = y_pred[..., 2:4]

    pred_box_abs, pred_obj, _ = decode_boxes(y_pred, anchors, num_classes)
    pred_box_corners = xywh_to_x1y1x2y2(pred_box_abs)

    true_obj = y_true[..., 4:5]
    true_class = y_true[..., 5:]
    true_box_rel = encode_boxes(y_true[..., 0:4], anchors)
    true_xy_rel = true_box_rel[..., 0:2]
    true_wh_rel = true_box_rel[..., 2:4]

    # small-box weighting: 2 - w*h (`yolov3.py:405-407`)
    weight = 2.0 - y_true[..., 2] * y_true[..., 3]
    obj = true_obj[..., 0]

    # xy / wh coordinate losses (`yolov3.py:515-563`)
    xy_loss = jnp.sum(jnp.square(true_xy_rel - pred_xy_rel), axis=-1)
    xy_loss = jnp.sum(obj * weight * xy_loss, axis=(1, 2, 3)) * LAMBDA_COORD
    wh_loss = jnp.sum(jnp.square(true_wh_rel - pred_wh_rel), axis=-1)
    wh_loss = jnp.sum(obj * weight * wh_loss, axis=(1, 2, 3)) * LAMBDA_COORD

    # class loss, only where an object is present (`yolov3.py:494-513`)
    class_bce = optax.sigmoid_binary_cross_entropy(
        y_pred[..., 5:], true_class)
    class_loss = jnp.sum(true_obj * class_bce, axis=(1, 2, 3, 4))

    # ignore mask: predictions overlapping ANY ground truth > 0.5 IoU are not
    # penalized for objectness; padded GT rows have zero area → IoU 0 → never
    # mask anything. Deliberate deviation from the reference
    # (`yolov3.py:448-454`): it derives the candidate boxes from this scale's
    # dense y_true — a GT assigned to another scale never ignores predictions
    # here, and its coordinate-wise `tf.sort` scrambles multi-box lists. We
    # follow darknet (yolo_layer.c: every truth is compared) using the exact
    # padded GT list; pinned vs the reference in
    # tests/test_yolo.py::test_loss_matches_reference_tf_implementation.
    b, g = y_pred.shape[0], y_pred.shape[1]
    flat_pred = pred_box_corners.reshape(b, -1, 4)
    masked_gt = gt_boxes * gt_valid[..., None].astype(gt_boxes.dtype)
    # fused pallas kernel on TPU (no (B, N, M) HBM intermediate), jnp elsewhere;
    # the mask is consumed through a `<` so its gradient is identically zero —
    # stop_gradient makes that explicit and keeps the kernel out of the VJP.
    from .pallas_kernels import best_iou_auto
    best_iou = jax.lax.stop_gradient(
        best_iou_auto(flat_pred, masked_gt)).reshape(b, g, g, 3)
    ignore_mask = (best_iou < IGNORE_THRESH).astype(jnp.float32)[..., None]

    # objectness loss (`yolov3.py:472-492`)
    obj_bce = optax.sigmoid_binary_cross_entropy(y_pred[..., 4:5], true_obj)
    obj_term = jnp.sum(true_obj * obj_bce, axis=(1, 2, 3, 4))
    noobj_term = jnp.sum((1.0 - true_obj) * obj_bce * ignore_mask,
                         axis=(1, 2, 3, 4)) * LAMBDA_NOOBJ
    obj_loss = obj_term + noobj_term

    total = xy_loss + wh_loss + class_loss + obj_loss
    return {"xy": xy_loss, "wh": wh_loss, "class": class_loss, "obj": obj_loss,
            "total": total}


def yolo_loss(y_trues, y_preds, gt_boxes, gt_valid, num_classes: int,
              anchors_wh=None) -> dict:
    """Sum the per-scale losses over the 3 scales (`YOLO/tensorflow/train.py:80-95`).
    Scale order = model output order: stride 8 (anchors 0-2) first.
    Returns dict of (B,) per-example components."""
    anchors = np.asarray(ANCHORS_WH if anchors_wh is None else anchors_wh)
    out = None
    for i, (y_true, y_pred) in enumerate(zip(y_trues, y_preds)):
        part = yolo_loss_one_scale(y_true, y_pred, gt_boxes, gt_valid,
                                   anchors[3 * i:3 * i + 3], num_classes)
        out = part if out is None else {k: out[k] + part[k] for k in out}
    return out
