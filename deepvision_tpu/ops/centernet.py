"""CenterNet label encoding, losses, and decoding — pure jnp, on-device.

The reference's ObjectsAsPoints family is WIP: its preprocessor's `make_label`
path is incomplete (`ObjectsAsPoints/tensorflow/preprocess.py:10-27` returns raw
bboxes), its trainer has no losses (`train.py:35`), and its runner is commented
out (`train.py:248`). This module completes the family per the "Objects as
Points" paper (Zhou et al. 2019) and the upstream CenterNet code the reference
cites (`model.py:16,25`):

- labels: per-class center heatmaps splatted with size-adaptive gaussians
  (CornerNet `gaussian_radius`, min_overlap 0.7), plus size (output-stride
  pixels) and center-offset targets at each object's center cell;
- losses: penalty-reduced pixelwise focal loss (α=2, β=4) on the heatmap,
  masked L1 on size (×0.1) and offset (×1), summed over stacks;
- decode: peak extraction as `p == maxpool3x3(p)` + top-k — the XLA-friendly
  replacement for NMS that is the paper's hallmark.

Everything uses the same padded (MAX_BOXES, 4) ground-truth layout as the YOLO
family (ops/yolo.py), so the detection data pipeline is shared.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from .yolo import MAX_BOXES  # shared ground-truth pad  # noqa: F401

SIZE_LOSS_WEIGHT = 0.1   # λ_size, paper §3
OFFSET_LOSS_WEIGHT = 1.0


def gaussian_radius(height: jnp.ndarray, width: jnp.ndarray,
                    min_overlap: float = 0.7) -> jnp.ndarray:
    """CornerNet radius: the largest r such that a corner shifted by r still
    yields IoU ≥ min_overlap. Elementwise over (N,) box sizes in output pixels."""
    a1 = 1.0
    b1 = height + width
    c1 = width * height * (1 - min_overlap) / (1 + min_overlap)
    sq1 = jnp.sqrt(jnp.maximum(b1 ** 2 - 4 * a1 * c1, 0.0))
    r1 = (b1 - sq1) / 2

    a2 = 4.0
    b2 = 2 * (height + width)
    c2 = (1 - min_overlap) * width * height
    sq2 = jnp.sqrt(jnp.maximum(b2 ** 2 - 4 * a2 * c2, 0.0))
    r2 = (b2 - sq2) / (2 * a2)

    a3 = 4.0 * min_overlap
    b3 = -2 * min_overlap * (height + width)
    c3 = (min_overlap - 1) * width * height
    sq3 = jnp.sqrt(jnp.maximum(b3 ** 2 - 4 * a3 * c3, 0.0))
    r3 = (b3 + sq3) / (2 * a3)
    return jnp.maximum(jnp.minimum(jnp.minimum(r1, r2), r3), 0.0)


def encode_labels_one(boxes: jnp.ndarray, classes: jnp.ndarray,
                      valid: jnp.ndarray, grid: int,
                      num_classes: int) -> Dict[str, jnp.ndarray]:
    """One example: padded corner boxes (N,4 normalized) → CenterNet targets.

    Returns {"heatmap": (g,g,C), "size": (g,g,2), "offset": (g,g,2),
    "mask": (g,g)} where size/offset/mask live at each object's center cell.
    """
    ok = valid.astype(bool)
    center = (boxes[:, 0:2] + boxes[:, 2:4]) / 2.0 * grid        # (N,2) x,y
    wh = (boxes[:, 2:4] - boxes[:, 0:2]) * grid                  # output px
    cell = jnp.floor(center).astype(jnp.int32)                   # (N,2)

    radius = jnp.maximum(gaussian_radius(wh[:, 1], wh[:, 0]), 1e-2)
    sigma = radius / 3.0

    xs = jnp.arange(grid, dtype=jnp.float32)
    dx = xs[None, :] - cell[:, 0, None].astype(jnp.float32)      # (N,g)
    dy = xs[None, :] - cell[:, 1, None].astype(jnp.float32)
    g2 = (dx[:, None, :] ** 2 + dy[:, :, None] ** 2)             # (N,g,g) [y,x]
    gauss = jnp.exp(-g2 / (2.0 * sigma[:, None, None] ** 2))
    gauss = jnp.where(ok[:, None, None], gauss, 0.0)

    # per-class max-splat: scatter-max the (g,g,N) stack into class channels
    heatmap = jnp.zeros((grid, grid, num_classes), jnp.float32)
    heatmap = heatmap.at[:, :, jnp.where(ok, classes, num_classes)].max(
        jnp.transpose(gauss, (1, 2, 0)), mode="drop")

    oob = jnp.int32(grid)
    gy = jnp.where(ok, cell[:, 1], oob)
    gx = jnp.where(ok, cell[:, 0], oob)
    size = jnp.zeros((grid, grid, 2), jnp.float32).at[gy, gx].set(
        wh, mode="drop")
    offset = jnp.zeros((grid, grid, 2), jnp.float32).at[gy, gx].set(
        center - cell.astype(jnp.float32), mode="drop")
    mask = jnp.zeros((grid, grid), jnp.float32).at[gy, gx].set(
        1.0, mode="drop")
    return {"heatmap": heatmap, "size": size, "offset": offset, "mask": mask}


def encode_labels(boxes, classes, valid, grid: int,
                  num_classes: int) -> Dict[str, jnp.ndarray]:
    """Batch version (vmapped): (B,N,4), (B,N), (B,N) → dict of (B,g,g,·)."""
    return jax.vmap(lambda b, c, v: encode_labels_one(b, c, v, grid,
                                                      num_classes))(
        boxes, classes, valid)


def focal_loss(pred_logits: jnp.ndarray, target: jnp.ndarray,
               axis_name=None) -> jnp.ndarray:
    """Penalty-reduced pixelwise focal loss (paper eq. 1), per example (B,).

    Normalized by the number of centers (target == 1 pixels), min 1.
    `axis_name`: mesh axis holding the rest of each example's rows (spatial
    shard_map path) — sums and center counts psum over it so the per-example
    normalization stays global.
    """
    p = jax.nn.sigmoid(pred_logits.astype(jnp.float32))
    p = jnp.clip(p, 1e-6, 1.0 - 1e-6)
    pos = (target >= 1.0 - 1e-6).astype(jnp.float32)
    pos_loss = pos * ((1 - p) ** 2) * jnp.log(p)
    neg_loss = (1 - pos) * ((1 - target) ** 4) * (p ** 2) * jnp.log(1 - p)
    s = jnp.sum(pos_loss + neg_loss, axis=(1, 2, 3))
    n_pos = jnp.sum(pos, axis=(1, 2, 3))
    if axis_name is not None:
        s = jax.lax.psum(s, axis_name)
        n_pos = jax.lax.psum(n_pos, axis_name)
    return -s / jnp.maximum(n_pos, 1.0)


def masked_l1_loss(pred: jnp.ndarray, target: jnp.ndarray,
                   mask: jnp.ndarray, axis_name=None) -> jnp.ndarray:
    """L1 at center cells only, normalized by center count, per example (B,)."""
    diff = jnp.sum(jnp.abs(pred.astype(jnp.float32) - target)
                   * mask[..., None], axis=(1, 2, 3))
    n = jnp.sum(mask, axis=(1, 2))
    if axis_name is not None:
        diff = jax.lax.psum(diff, axis_name)
        n = jax.lax.psum(n, axis_name)
    return diff / jnp.maximum(n, 1.0)


def centernet_loss(outputs: Sequence[Dict[str, jnp.ndarray]],
                   targets: Dict[str, jnp.ndarray],
                   axis_name=None) -> Dict[str, jnp.ndarray]:
    """Sum per-stack losses (intermediate supervision) → dict of (B,).
    `axis_name` threads to the per-example sums (spatial shard_map path)."""
    hm = size = off = 0.0
    for out in outputs:
        hm = hm + focal_loss(out["heatmap"], targets["heatmap"],
                             axis_name=axis_name)
        size = size + masked_l1_loss(out["size"], targets["size"],
                                     targets["mask"], axis_name=axis_name)
        off = off + masked_l1_loss(out["offset"], targets["offset"],
                                   targets["mask"], axis_name=axis_name)
    total = hm + SIZE_LOSS_WEIGHT * size + OFFSET_LOSS_WEIGHT * off
    return {"heatmap": hm, "size": size, "offset": off, "total": total}


def decode(head: Dict[str, jnp.ndarray], *, max_detections: int = 100
           ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Peaks → detections (paper §3: "3×3 max pooling… replaces NMS").

    head: {"heatmap" (B,g,g,C) logits, "size" (B,g,g,2), "offset" (B,g,g,2)}.
    Returns (boxes (B,K,4) normalized corners, scores (B,K), classes (B,K)).
    """
    hm = jax.nn.sigmoid(head["heatmap"].astype(jnp.float32))
    peak = jax.lax.reduce_window(hm, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                                 (1, 1, 1, 1), "SAME")
    hm = jnp.where(hm == peak, hm, 0.0)

    b, g = hm.shape[0], hm.shape[1]
    num_classes = hm.shape[-1]
    flat = hm.reshape(b, -1)                          # (B, g*g*C)
    scores, idx = jax.lax.top_k(flat, max_detections)
    cls = (idx % num_classes).astype(jnp.int32)
    cell = idx // num_classes
    cy = (cell // g).astype(jnp.int32)
    cx = (cell % g).astype(jnp.int32)

    take = jax.vmap(lambda m, y, x: m[y, x])          # gather per batch
    off = take(head["offset"].astype(jnp.float32), cy, cx)   # (B,K,2)
    wh = take(head["size"].astype(jnp.float32), cy, cx)

    px = cx.astype(jnp.float32) + off[..., 0]
    py = cy.astype(jnp.float32) + off[..., 1]
    x1 = (px - wh[..., 0] / 2) / g
    y1 = (py - wh[..., 1] / 2) / g
    x2 = (px + wh[..., 0] / 2) / g
    y2 = (py + wh[..., 1] / 2) / g
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    return boxes, scores, cls
