"""Per-model training configs — the `training_config` registry surface of the
reference (`ResNet/pytorch/train.py:26-215`, selected by `-m <name>`), as typed
dataclasses. Hyperparameters are paper-cited; where the reference's single-GPU recipe
conflicts with the large-batch TPU recipe (BASELINE.md: ResNet-50 must reach 75.3%),
the TPU recipe wins and the difference is noted.
"""

from __future__ import annotations

from .core.config import (DataConfig, OptimizerConfig, ScheduleConfig, TrainConfig)
from .utils.registry import CONFIGS


def _imagenet(image_size=224, **kw):
    return DataConfig(dataset="imagenet", image_size=image_size, num_classes=1000,
                      train_examples=1281167, val_examples=50000, **kw)


# -- LeNet (reference: LeNet/pytorch/train.py:15-32 — Adam, MNIST) -------------
CONFIGS.register("lenet5", TrainConfig(
    name="lenet5", model="lenet5", batch_size=256, total_epochs=20,
    optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
    schedule=ScheduleConfig(name="plateau", plateau_patience=2, plateau_mode="max"),
    data=DataConfig(dataset="mnist", image_size=32, channels=1, num_classes=10,
                    train_examples=60000, val_examples=10000),
    dtype="float32",
))

# -- LeNet on real bundled digits (the zero-egress real-data accuracy gate:
#    scikit-learn's UCI handwritten digits upsampled to 32px through the
#    unchanged lenet5 model; see data/digits.py. Committed artifact:
#    runs/r04_lenet5_digits. The reference's published MNIST numbers are
#    99.07% (`LeNet/pytorch/README.md:47`) / 98.58% (`LeNet/tensorflow/
#    README.md:41`); the gated real-MNIST test in tests/test_real_data.py
#    asserts >=98.5% when the idx images are fetched.) ------------------------
CONFIGS.register("lenet5_digits", TrainConfig(
    name="lenet5_digits", model="lenet5", batch_size=128, total_epochs=60,
    optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
    schedule=ScheduleConfig(name="plateau", plateau_patience=5,
                            plateau_mode="max"),
    data=DataConfig(dataset="digits", image_size=32, channels=1,
                    num_classes=10, train_examples=1437, val_examples=360),
    dtype="float32",
))

# -- AlexNet (Krizhevsky 2012 §5: SGD momentum .9, wd 5e-4, lr .01 /10 on plateau;
#    reference alexnet configs mirror this) ------------------------------------
for _name in ("alexnet1", "alexnet2"):
    CONFIGS.register(_name, TrainConfig(
        name=_name, model=_name, batch_size=128, total_epochs=90,
        optimizer=OptimizerConfig(name="momentum", learning_rate=0.01, momentum=0.9,
                                  weight_decay=5e-4),
        schedule=ScheduleConfig(name="plateau", plateau_patience=2,
                                plateau_factor=0.1, plateau_mode="max"),
        data=_imagenet(227 if _name == "alexnet1" else 224),
    ))

# -- VGG (Simonyan 2014 §3.1: batch 256, momentum .9, wd 5e-4, lr .01 /10 on
#    plateau, dropout .5) -------------------------------------------------------
for _name in ("vgg16", "vgg19"):
    CONFIGS.register(_name, TrainConfig(
        name=_name, model=_name, batch_size=256, total_epochs=74,
        optimizer=OptimizerConfig(name="momentum", learning_rate=0.01, momentum=0.9,
                                  weight_decay=5e-4),
        schedule=ScheduleConfig(name="plateau", plateau_patience=2,
                                plateau_factor=0.1, plateau_mode="max"),
        data=_imagenet(),
    ))

# -- Inception V1 (Szegedy 2014 §5: momentum .9, lr decreased 4% every 8 epochs;
#    aux heads weighted 0.3 — the reference never wired the aux losses, fixed
#    here via aux_loss_weight) --------------------------------------------------
CONFIGS.register("inception_v1", TrainConfig(
    name="inception_v1", model="inception_v1", batch_size=256, total_epochs=90,
    optimizer=OptimizerConfig(name="momentum", learning_rate=0.05, momentum=0.9,
                              weight_decay=1e-4),
    schedule=ScheduleConfig(name="step", warmup_epochs=2,
                            boundaries_epochs=tuple(range(8, 90, 8)),
                            decay_factor=0.96 ** 8),
    aux_loss_weight=0.3,
    data=_imagenet(),
))

# -- Inception V3 (Szegedy 2015 §8: RMSprop decay .9 eps 1.0, lr .045 ×0.94 every
#    2 epochs, grad clip 2.0, label smoothing .1, 299px) ------------------------
CONFIGS.register("inception_v3", TrainConfig(
    name="inception_v3", model="inception_v3", batch_size=256, total_epochs=100,
    optimizer=OptimizerConfig(name="rmsprop", learning_rate=0.045, rmsprop_decay=0.9,
                              eps=1.0, grad_clip_norm=2.0),
    schedule=ScheduleConfig(name="step", boundaries_epochs=tuple(range(2, 100, 2)),
                            decay_factor=0.94),
    label_smoothing=0.1, aux_loss_weight=0.3,
    data=_imagenet(299),
))

# -- ResNet (He 2015 §3.4: batch 256, lr .1, /10 at plateau, momentum .9, wd 1e-4.
#    TPU recipe: warmup 5 epochs + cosine to 90, label smoothing .1 — needed for
#    the 75.3% BASELINE.md bar; plateau kept available via schedule.name) -------
for _name in ("resnet34", "resnet50", "resnet101", "resnet152", "resnet50v2"):
    CONFIGS.register(_name, TrainConfig(
        name=_name, model=_name, batch_size=256, total_epochs=90,
        # base_batch_size → linear LR scaling when --batch-size is raised for
        # pod runs (lr 0.1 @ 256 scales to 3.2 @ 8192, Goyal et al. recipe)
        optimizer=OptimizerConfig(name="momentum", learning_rate=0.1, momentum=0.9,
                                  weight_decay=1e-4, base_batch_size=256),
        schedule=ScheduleConfig(name="cosine", warmup_epochs=5),
        label_smoothing=0.1,
        data=_imagenet(),
    ))

# -- ResNet-50 TPU north-star recipe (BASELINE.md: 75.3% top-1, ≤2h on a pod).
#    The full large-batch recipe as ONE named config instead of scattered
#    opt-in flags (Goyal et al. 2017; He et al. 2019 bag-of-tricks):
#    cosine + 5-epoch warmup, linear LR scaling from base 256 (0.1@256 →
#    3.2@8192 when launched with --batch-size 8192), label smoothing 0.1,
#    no weight decay on BN scale/bias or conv/dense biases, EMA eval weights.
#    Same model as `resnet50`; only the recipe differs. Default batch 1024
#    (128/chip on a v5e-8); raise --batch-size to the pod's capacity — the
#    LR, schedule, and divergence guard all scale with it. Pod playbook:
#    README.md "ResNet-50 pod recipe".
CONFIGS.register("resnet50_tpu", TrainConfig(
    name="resnet50_tpu", model="resnet50", batch_size=1024, total_epochs=90,
    optimizer=OptimizerConfig(name="momentum", learning_rate=0.1, momentum=0.9,
                              weight_decay=1e-4, base_batch_size=256,
                              no_decay_bn_bias=True),
    schedule=ScheduleConfig(name="cosine", warmup_epochs=5),
    label_smoothing=0.1, ema_decay=0.9999,
    data=_imagenet(),
))

# -- MobileNet V1 (Howard 2017 §4: RMSprop, less wd on depthwise; simplified to
#    the common cosine recipe; reference config `MobileNet/pytorch/train.py`) ---
CONFIGS.register("mobilenet_v1", TrainConfig(
    name="mobilenet_v1", model="mobilenet_v1", batch_size=256, total_epochs=90,
    optimizer=OptimizerConfig(name="rmsprop", learning_rate=0.045, rmsprop_decay=0.9,
                              weight_decay=4e-5),
    schedule=ScheduleConfig(name="step", boundaries_epochs=tuple(range(2, 90, 2)),
                            decay_factor=0.94),
    data=_imagenet(),
))

# -- ShuffleNet V1 (Zhang 2017 §4: BN no-decay, linear-decay LR over 3e5 steps;
#    reference left the model an empty stub — completed here) -------------------
CONFIGS.register("shufflenet_v1", TrainConfig(
    name="shufflenet_v1", model="shufflenet_v1", batch_size=512, total_epochs=90,
    optimizer=OptimizerConfig(name="momentum", learning_rate=0.25, momentum=0.9,
                              weight_decay=4e-5),
    schedule=ScheduleConfig(name="linear_decay", decay_start_epoch=0),
    label_smoothing=0.1,
    data=_imagenet(),
))


# -- DCGAN (DCGAN/tensorflow/main.py:13-16,31-32: MNIST, batch 256, 50 epochs,
#    two Adam(1e-4) optimizers, checkpoint every 2 epochs keep 3) ---------------
CONFIGS.register("dcgan", TrainConfig(
    name="dcgan", model="dcgan", family="gan", batch_size=256, total_epochs=50,
    optimizer=OptimizerConfig(name="adam", learning_rate=1e-4),
    schedule=ScheduleConfig(name="constant"),
    data=DataConfig(dataset="mnist", image_size=28, channels=1, num_classes=10,
                    train_examples=60000, val_examples=10000),
    dtype="float32", keep_checkpoints=3, keep_best=False,
))

# -- CycleGAN (CycleGAN/tensorflow/train.py:14-21: 200 epochs, Adam lr 2e-4
#    β1 .5, linear LR decay to 0 after epoch 100, λ_cycle 10 λ_id 5. The
#    reference default batch is 4 on one GPU; the global batch must divide the
#    data axis, so the default is 1/chip on a v3-8) -----------------------------
CONFIGS.register("cyclegan", TrainConfig(
    name="cyclegan", model="cyclegan", family="gan", batch_size=8,
    total_epochs=200,
    optimizer=OptimizerConfig(name="adam", learning_rate=2e-4, beta1=0.5),
    schedule=ScheduleConfig(name="linear_decay", decay_start_epoch=100),
    data=DataConfig(dataset="cyclegan", image_size=256, num_classes=0,
                    train_examples=1000, val_examples=100),
    dtype="float32", keep_checkpoints=3, keep_best=False,
))

# -- Stacked Hourglass (Hourglass/tensorflow/main.py:26 lr 1e-3 default,
#    train.py:233-236 batch 16/replica, Adam; MPII 16 joints at 256px → 64px
#    heatmaps; plateau /10 after 10 bad epochs watching val loss) ---------------
CONFIGS.register("hourglass104", TrainConfig(
    name="hourglass104", model="hourglass104", family="pose", batch_size=128,
    total_epochs=100,
    optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
    schedule=ScheduleConfig(name="plateau", plateau_patience=10,
                            plateau_factor=0.1, plateau_mode="min"),
    data=DataConfig(dataset="pose", image_size=256, num_classes=16,
                    train_examples=22246, val_examples=2958),
))

# -- YOLO V3 (reference module constants YOLO/tensorflow/train.py:13-17: 416px,
#    batch 16/replica, 300 epochs, COCO 80 classes; Adam lr .01 with hand-rolled
#    plateau /10 after 10 bad epochs watching val loss, train.py:46-68) ---------
CONFIGS.register("yolov3", TrainConfig(
    name="yolov3", model="yolov3", family="detection", batch_size=128,
    total_epochs=300,
    optimizer=OptimizerConfig(name="adam", learning_rate=0.01),
    schedule=ScheduleConfig(name="plateau", plateau_patience=10,
                            plateau_factor=0.1, plateau_mode="min"),
    data=DataConfig(dataset="detection", image_size=416, num_classes=80,
                    train_examples=118287, val_examples=5000),
))

# -- YOLO V3 on VOC2007 (the reference's 1×K80 recipe, YOLO/tensorflow/README.md:10;
#    20 classes, 2501 trainval images) ------------------------------------------
CONFIGS.register("yolov3_voc", TrainConfig(
    name="yolov3_voc", model="yolov3", family="detection", batch_size=32,
    total_epochs=300,
    model_kwargs={"num_classes": 20},
    optimizer=OptimizerConfig(name="adam", learning_rate=0.01),
    schedule=ScheduleConfig(name="plateau", plateau_patience=10,
                            plateau_factor=0.1, plateau_mode="min"),
    data=DataConfig(dataset="detection", image_size=416, num_classes=20,
                    train_examples=2501, val_examples=2510),
))


# -- YOLO on real scanned-digit detection scenes: the SAME offline
#    real-data detection gate as centernet_digits, through the family the
#    round-4 VERDICT named (item 7); committed run runs/r05_yolov3_digits_cpu.
#    width_mult sizes Darknet-53 for a CPU-feasible committed run; grids at
#    64px are (8, 4, 2).
CONFIGS.register("yolov3_digits", TrainConfig(
    name="yolov3_digits", model="yolov3", family="detection", batch_size=32,
    total_epochs=150,  # anchor-based heads need far more steps than
                       # CenterNet's focal head at this scene count
    model_kwargs={"num_classes": 10, "width_mult": 0.25},
    optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
    schedule=ScheduleConfig(name="step", boundaries_epochs=(70, 90),
                            decay_factor=0.1),
    # 128px canvas, NOT 64: the 16px digits are then 0.125-normalized,
    # which best-matches the MEDIUM COCO anchor -> the 8x8 grid, where the
    # quadrant composition guarantees one digit per cell. At 64px the same
    # digits are 0.25-normalized, best-match the LARGE anchor, and every
    # label collapses onto the 2x2 coarse grid (measured round 5:
    # mAP@0.5 = 0.07 no matter how long it trains).
    data=DataConfig(dataset="digits_detect", image_size=128, num_classes=10,
                    train_examples=512, val_examples=128),
))


# -- CenterNet / ObjectsAsPoints (ObjectsAsPoints/tensorflow/model.py:130-131:
#    256px 2-stack hourglass, COCO 80 classes; the reference trainer was never
#    wired — recipe per Zhou 2019 §5.2 adapted to the plateau convention) ------
_CENTERNET = TrainConfig(
    name="centernet", model="centernet", family="centernet", batch_size=64,
    total_epochs=140,
    optimizer=OptimizerConfig(name="adam", learning_rate=1.25e-4),
    schedule=ScheduleConfig(name="step", boundaries_epochs=(90, 120),
                            decay_factor=0.1),
    data=DataConfig(dataset="detection", image_size=256, num_classes=80,
                    train_examples=118287, val_examples=5000),
)
CONFIGS.register("centernet", _CENTERNET)
# the reference names the family ObjectsAsPoints; accept the paper name too
# (own name → own runs/objects_as_points workdir, no checkpoint clobbering)
CONFIGS.register("objects_as_points", _CENTERNET.replace(
    name="objects_as_points"))
# -- CenterNet on real scanned-digit detection scenes (the zero-egress
#    real-data DETECTION gate, data/digits.py::detection_splits — detection
#    analog of lenet5_digits; the reference never published an mAP,
#    `YOLO/tensorflow/README.md:29`. Tiny hourglass: 64px canvas -> 16px
#    grid needs order<=4; width/stacks sized for a CPU-feasible committed
#    run, runs/r05_centernet_digits_cpu) --------------------------------------
CONFIGS.register("centernet_digits", _CENTERNET.replace(
    name="centernet_digits", batch_size=32, total_epochs=30,
    model_kwargs={"num_stack": 1, "order": 2, "width_mult": 0.25},
    optimizer=OptimizerConfig(name="adam", learning_rate=5e-4),
    schedule=ScheduleConfig(name="step", boundaries_epochs=(20, 26),
                            decay_factor=0.1),
    data=DataConfig(dataset="digits_detect", image_size=64, num_classes=10,
                    train_examples=512, val_examples=128),
))


# -- Semantic segmentation (U-Net decoder over the ResNet backbones —
#    models/segment.py; the zoo's first dense-prediction family, beyond the
#    reference's classification/detection/pose/GAN coverage, PAPER.md §0).
#    Flagship: ResNet-50 encoder at 224px, 21 classes (the VOC convention),
#    the standard momentum/poly-ish cosine recipe. The dataset defaults to
#    the synthetic shapes backend (data/segmentation.py) — point --data-dir
#    at a real corpus once a TFRecord seg recipe lands; the REAL-pixel gate
#    meanwhile is unet_digits below, the exact yolov3_digits pattern. -------
CONFIGS.register("unet_resnet50", TrainConfig(
    name="unet_resnet50", model="unet_resnet50", family="segmentation",
    batch_size=32, total_epochs=60,
    optimizer=OptimizerConfig(name="momentum", learning_rate=0.02,
                              momentum=0.9, weight_decay=1e-4,
                              base_batch_size=32),
    schedule=ScheduleConfig(name="cosine", warmup_epochs=2),
    data=DataConfig(dataset="seg_synthetic", image_size=224, num_classes=21,
                    train_examples=2048, val_examples=256,
                    mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
))

# -- CPU-feasible synthetic recipe: the smoke/parity/preflight surface (the
#    lenet5-of-segmentation). Tiny BasicBlock encoder (models/segment.py
#    unet_small), 64px shapes-and-masks scenes. f32 so the virtual-mesh
#    parity pins are tight. ---------------------------------------------------
CONFIGS.register("unet_synthetic", TrainConfig(
    name="unet_synthetic", model="unet_small", family="segmentation",
    batch_size=32, total_epochs=8,
    optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
    schedule=ScheduleConfig(name="constant"),
    data=DataConfig(dataset="seg_synthetic", image_size=64, num_classes=6,
                    train_examples=256, val_examples=64,
                    mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
    dtype="float32",
))

# -- The H-sharded variant BY NAME: same recipe with the spatial mesh and the
#    owned-collectives backend pre-selected — `-m unet_synthetic_sp2` on any
#    host whose per-process device count divides by 2 trains with H sharded
#    end to end (images, masks, logits; parallel/spatial_shard.py). The
#    equivalent ad-hoc launch is `-m unet_synthetic --spatial-parallel 2`. ----
CONFIGS.register("unet_synthetic_sp2", CONFIGS.get("unet_synthetic").replace(
    name="unet_synthetic_sp2", spatial_parallel=2,
    spatial_backend="shard_map"))

# -- Real scanned-digit segmentation scenes: the zero-egress REAL-data gate
#    for the family (data/segmentation.py::segmentation_scenes — real UCI
#    handwriting pasted into scenes, per-pixel ground truth from the digit's
#    own stroke pixels; 11 classes = background + 10 digits). Follows the
#    yolov3_digits recipe shape; exercises the xent+dice loss. ---------------
CONFIGS.register("unet_digits", TrainConfig(
    name="unet_digits", model="unet_small", family="segmentation",
    batch_size=32, total_epochs=30, loss="xent_dice",
    optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
    schedule=ScheduleConfig(name="step", boundaries_epochs=(20, 26),
                            decay_factor=0.1),
    data=DataConfig(dataset="digits_seg", image_size=64, num_classes=11,
                    train_examples=512, val_examples=128,
                    mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5)),
    dtype="float32",
))


# -- Vision Transformer (Dosovitskiy 2021; ROADMAP item 2 — the first
#    non-ConvNet family, pairing with the Pallas fused-attention kernel in
#    ops/attention.py). `attention_impl="auto"` lowers the flash kernel on
#    TPU and the naive einsum path on CPU (docs/ATTENTION.md).
#
#    vit_tiny: the CPU-feasible smoke/parity/preflight surface on the
#    synthetic loader — 32px / patch 8 → 17 tokens, d=192, 3 heads of 64.
#    Internal dims (192/768/17) avoid num_classes (10) so
#    `serving_head_dims` stays unambiguous for the dtype and quant rules. ----
CONFIGS.register("vit_tiny", TrainConfig(
    name="vit_tiny", model="vit", batch_size=32, total_epochs=4,
    model_kwargs={"patch_size": 8, "embed_dim": 192, "depth": 4,
                  "num_heads": 3, "mlp_dim": 768, "attention_impl": "auto"},
    optimizer=OptimizerConfig(name="adam", learning_rate=1e-3),
    schedule=ScheduleConfig(name="constant"),
    data=DataConfig(dataset="synthetic", image_size=32, channels=3,
                    num_classes=10, train_examples=512, val_examples=128),
))

# -- ViT-Small/16 on the flattened-dir ImageNet loader (DeiT-style recipe:
#    AdamW-ish adam + cosine warmup; 224px / patch 16 → 197 tokens, d=384,
#    6 heads of 64 — the seq length the bench pins (196 patches + cls)). ----
CONFIGS.register("vit_small", TrainConfig(
    name="vit_small", model="vit", batch_size=256, total_epochs=90,
    model_kwargs={"patch_size": 16, "embed_dim": 384, "depth": 8,
                  "num_heads": 6, "mlp_dim": 1536, "dropout_rate": 0.1,
                  "attention_impl": "auto"},
    optimizer=OptimizerConfig(name="adam", learning_rate=1e-3,
                              weight_decay=5e-2, grad_clip_norm=1.0),
    schedule=ScheduleConfig(name="cosine", warmup_epochs=5),
    label_smoothing=0.1,
    data=DataConfig(dataset="imagenet_flat", image_size=224, num_classes=1000,
                    train_examples=1281167, val_examples=50000),
))


def get_config(name: str) -> TrainConfig:
    return CONFIGS.get(name)


# Adversarial configs use the two-network AdversarialTrainer machinery in
# core/gan.py, not the supervised Trainer families. Derived from the configs'
# own `family` field so it cannot drift from the registry.
GAN_CONFIGS = frozenset(
    n for n in CONFIGS.names() if CONFIGS.get(n).family == "gan")


def trainer_class_for_config(name: str):
    """Supervised trainer class for a config name, used by the tools that
    accept ANY config (tools/verify_mesh.py, tools/preflight.py). Dispatches
    on the config's own `family` field (set at registration), so a newly
    registered config carries its trainer with it. Returns None for
    adversarial configs (AdversarialTrainer machinery, core/gan.py)."""
    family = CONFIGS.get(name).family
    if family == "gan":
        return None
    from .core.centernet import CenterNetTrainer
    from .core.detection import DetectionTrainer
    from .core.pose import PoseTrainer
    from .core.segment import SegmentationTrainer
    from .core.trainer import Trainer
    classes = {"classification": Trainer, "detection": DetectionTrainer,
               "pose": PoseTrainer, "centernet": CenterNetTrainer,
               "segmentation": SegmentationTrainer}
    if family not in classes:
        raise ValueError(
            f"config {name!r} declares unknown trainer family {family!r}; "
            f"expected one of {sorted(classes) + ['gan']}")
    return classes[family]
