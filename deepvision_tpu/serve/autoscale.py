"""Shed-driven autoscaling + per-model circuit breaking (overload control).

The load bench (bench_serve.py --load) measures what happens when offered
traffic exceeds capacity: queues fill, p99 explodes, requests shed. Until
now the fleet could only WATCH that happen — one dispatcher worker per
model was all the capacity there would ever be, and a model whose dispatch
path broke kept eating (and timing out) every request sent to it. This
module closes both control loops:

**AutoscaleController** — a sampling loop over the fleet's per-model
`ServingMetrics`. The key fact that makes serving-side autoscaling nearly
free here: a dispatcher worker is a thread plus a reference to the SHARED
AOT bucket cache (`DynamicBatcher.set_workers`), so scaling up costs zero
recompiles and ~zero memory — unlike training, where more capacity means
more chips. The loop samples lifetime totals (deltas of shed + admission
refusals — evidence a concurrent metrics flush can't zero) plus queue
depth and rolling p99 against the model's documented p99 bound
(`max_delay_ms + one max-bucket compute time`, docs/SERVING.md), and:

- scales UP one worker after `up_after` consecutive overloaded samples
  (sustained shed, or p99 blown past `p99_factor` x bound with a standing
  queue) — hysteresis, so one bursty sample never spawns a thread;
- scales DOWN one worker after `down_after` consecutive idle samples
  (no shed, empty queue) — deliberately much slower than up, because the
  cost asymmetry is extreme: an idle thread costs nothing, a missing one
  sheds traffic;
- never leaves `[min_workers, max_workers]`, and observes a `cooldown_s`
  between decisions so it measures the EFFECT of the last one before
  taking the next.

Mesh-aware ordering (docs/SERVING.md 'Mesh serving'): on a GSPMD-sharded
engine a dispatcher worker is STILL just a thread over the shared sharded
AOT cache — the free lever — so the controller always exhausts workers
WITHIN the mesh first. When the ceiling is reached and the model is still
shedding, the next lever is a replica across meshes (a whole new mesh
worth of chips + compiles, owned by the PR 16 tier): the controller
ESCALATES instead of silently saturating — `escalations` counts it,
`wants_scale_out` flags it on /healthz (the tier router aggregates the
flag per replica), the optional `scale_out` hook is invoked, and the flag
drops as soon as a sweep finds the pressure gone.

Every decision is logged to the `resilience_` metrics stream
(core/resilience.log_resilience_event), printed to stderr, and surfaced
per model on `/healthz` and `/stats`.

**CircuitBreaker** — per-model fail-fast. K consecutive dispatch errors
open the circuit: `submit` answers `CircuitOpen` (HTTP 503 naming the
model) immediately instead of queueing requests behind a broken dispatch
path. After `cooldown_s` the breaker goes half-open and admits ONE probe
request; a successful dispatch closes it (any success closes it — a
working path is a working path), a failed probe re-opens it for another
cooldown. Deterministically testable via
`DEEPVISION_FAULT_SERVE_DISPATCH_FAIL=<k>:<n>` (utils/faults.py).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, Iterable, Optional

from ..core.resilience import log_resilience_event

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-model dispatch circuit: closed -> (K consecutive errors) ->
    open -> (cooldown) -> half-open probe -> closed | re-open.

    `reject_for()` is the submit-path check: None admits the request,
    a float is the seconds until the next half-open probe (the 503's
    Retry-After). `record(ok)` is called by the dispatcher with every
    dispatch outcome. All transitions are logged (resilience_ stream +
    stderr) and counted for /healthz."""

    def __init__(self, name: str, *, k: int = 5, cooldown_s: float = 5.0,
                 logger=None):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        self.name = name
        self.k = int(k)
        self.cooldown_s = float(cooldown_s)
        self.logger = logger
        self._lock = threading.Lock()
        self.state = CLOSED
        self._consecutive = 0
        self._open_until = 0.0
        self._probe_started: Optional[float] = None
        self._events = 0
        self.opened = 0      # transition counters (monotonic, /healthz)
        self.reopened = 0
        self.closed_after_open = 0

    def reject_for(self) -> Optional[float]:
        """None = admit; else seconds until a probe will be admitted."""
        with self._lock:
            if self.state == CLOSED:
                return None
            now = time.monotonic()
            if self.state == OPEN:
                if now < self._open_until:
                    return self._open_until - now
                self.state = HALF_OPEN          # cooldown over: probe time
                self._probe_started = None
            # half-open: exactly one probe in flight. If an admitted probe
            # never produced a record() (refused later in submit, client
            # abandoned it), a fresh probe is allowed after one cooldown —
            # a lost probe must not wedge the breaker open forever.
            if (self._probe_started is not None
                    and now - self._probe_started < self.cooldown_s):
                return self._probe_started + self.cooldown_s - now
            self._probe_started = now
            return None

    def record(self, ok: bool, trace_ref: Optional[str] = None) -> None:
        """Dispatch outcome feed (called by DynamicBatcher._dispatch).
        `trace_ref` names the span of the dispatch that produced this
        outcome (``span:<id>``), so a breaker transition's resilience
        event joins back to the exact batch that tripped it."""
        transition = None
        with self._lock:
            if ok:
                self._consecutive = 0
                if self.state != CLOSED:
                    # ANY success closes — including a straggler batch that
                    # was admitted before the circuit opened: evidence the
                    # path works is evidence the path works
                    self.state = CLOSED
                    self._probe_started = None
                    self.closed_after_open += 1
                    transition = "closed"
            else:
                self._consecutive += 1
                if self.state == HALF_OPEN:
                    self.state = OPEN
                    self._open_until = time.monotonic() + self.cooldown_s
                    self._probe_started = None
                    self.reopened += 1
                    transition = "reopened"
                elif self.state == CLOSED and self._consecutive >= self.k:
                    self.state = OPEN
                    self._open_until = time.monotonic() + self.cooldown_s
                    self.opened += 1
                    transition = "opened"
            consecutive = self._consecutive
            if transition is not None:
                # event seq allocated under the lock: concurrent dispatcher
                # workers feed record() and a read-increment-read outside
                # the guard can collide or skip sequence numbers
                self._events += 1
                seq = self._events
        if transition is not None:
            log_resilience_event(self.logger, seq,
                                 {f"breaker_{transition}": 1.0,
                                  "breaker_consecutive_errors":
                                      float(consecutive)},
                                 trace_ref=trace_ref)
            print(f"[serve-breaker:{self.name}] circuit {transition}"
                  + (f" after {consecutive} consecutive dispatch errors "
                     f"(fail-fast 503 for {self.cooldown_s:g}s, then a "
                     f"half-open probe)" if transition != "closed"
                     else " (dispatch healthy again — traffic restored)"),
                  file=sys.stderr, flush=True)

    def describe(self) -> dict:
        with self._lock:
            return {"state": self.state, "k": self.k,
                    "cooldown_s": self.cooldown_s,
                    "consecutive_errors": self._consecutive,
                    "opened": self.opened, "reopened": self.reopened,
                    "closed_after_open": self.closed_after_open}


class AutoscaleController:
    """Background control loop over the fleet's served models (same
    lifecycle shape as reload.WeightReloader: `start()` spawns the daemon
    sampler, `check_once()` runs one sweep synchronously — the tests' and
    preflight's handle — `stop()` joins)."""

    def __init__(self, models: Iterable, *,
                 interval_s: float = 1.0,
                 min_workers: int = 1,
                 max_workers: int = 4,
                 up_after: int = 2,
                 down_after: int = 10,
                 cooldown_s: float = 2.0,
                 p99_factor: float = 2.0,
                 scale_out=None,
                 logger=None):
        if max_workers < min_workers:
            raise ValueError(f"max_workers={max_workers} below "
                             f"min_workers={min_workers}")
        self.models = list(models)
        self.interval_s = float(interval_s)
        self.min_workers = max(1, int(min_workers))
        self.max_workers = int(max_workers)
        self.up_after = max(1, int(up_after))
        self.down_after = max(1, int(down_after))
        self.cooldown_s = float(cooldown_s)
        self.p99_factor = float(p99_factor)
        # across-mesh lever: called as scale_out(sm, refused=, queue_depth=)
        # when within-mesh workers are exhausted and the model still sheds
        # (e.g. a tier supervisor adding a replica); None = flag-only
        self.scale_out = scale_out
        self.logger = logger
        self._state: Dict[str, dict] = {
            sm.name: {"last": sm.metrics.totals(), "up_streak": 0,
                      "idle_streak": 0, "last_change": 0.0}
            for sm in self.models}
        self._events = 0
        # serializes sampling sweeps: check_once() is public (tests and
        # operators call it) and races the daemon _loop thread on the
        # per-model streak/totals state otherwise
        self._sample_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "AutoscaleController":
        if self._thread is None and self.models and self.interval_s > 0:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="autoscaler")
            self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception as e:  # noqa: BLE001 — the sampler must
                # survive a transiently weird metrics read; next tick retries
                print(f"[serve-autoscale] sample failed (will retry): {e!r}",
                      file=sys.stderr, flush=True)

    # -- one sweep ---------------------------------------------------------

    def check_once(self) -> int:
        """Sample every model once; returns how many scaling decisions
        were taken this sweep."""
        decisions = 0
        with self._sample_lock:
            for sm in self.models:
                if self._check_model(sm):
                    decisions += 1
        return decisions

    def _p99_bound_ms(self, sm) -> Optional[float]:
        """The model's documented latency contract: max_delay + one
        max-bucket compute time (docs/SERVING.md). Measured once per model
        (5 warm dispatches) and cached on the ServedModel; engines without
        a measurement hook (test stubs) simply skip the p99 signal."""
        bound = getattr(sm, "p99_bound_ms", None)
        if bound is not None:
            return bound
        measure = getattr(sm.engine, "measure_batch_ms", None)
        if measure is None:
            return None
        bound = sm.batcher.max_delay * 1000.0 + measure()
        sm.p99_bound_ms = bound
        return bound

    def _check_model(self, sm) -> bool:
        st = self._state[sm.name]
        totals = sm.metrics.totals()
        last, st["last"] = st["last"], totals
        # overload evidence: requests refused for capacity reasons since
        # the last sample — backpressure shed AND admission refusals (both
        # mean "the queue could not absorb the offered rate"); breaker
        # rejections are a broken dispatch path, not missing capacity
        refused = ((totals["shed"] - last["shed"])
                   + (totals["admission_rejected"]
                      - last["admission_rejected"]))
        queue_depth = sm.batcher.queue_depth
        workers = sm.batcher.workers
        overload = refused > 0
        if not overload:
            bound = self._p99_bound_ms(sm)
            if bound:
                p99 = sm.metrics.snapshot().get("p99_ms", 0.0)
                overload = (p99 > self.p99_factor * bound
                            and queue_depth >= sm.batcher.max_batch)
        now = time.monotonic()
        if not overload and st.get("wants_scale_out"):
            # pressure receded without a scale-out: drop the escalation
            # flag so /healthz stops advertising a want that expired
            st["wants_scale_out"] = False
            with sm.reload_lock:
                sm.autoscale_stats["wants_scale_out"] = False
        if overload:
            st["up_streak"] += 1
            st["idle_streak"] = 0
            if (st["up_streak"] >= self.up_after
                    and now - st["last_change"] >= self.cooldown_s):
                if workers < self.max_workers:
                    st["up_streak"] = 0
                    st["last_change"] = now
                    sm.batcher.set_workers(workers + 1)
                    self._decide(sm, "scale_up", workers + 1,
                                 refused=refused, queue_depth=queue_depth)
                    return True
                # worker ceiling reached and still shedding: within-mesh
                # capacity is exhausted — escalate to the across-mesh lever
                st["up_streak"] = 0
                st["last_change"] = now
                self._escalate(sm, refused=refused, queue_depth=queue_depth)
                return True
        elif queue_depth == 0:
            st["idle_streak"] += 1
            st["up_streak"] = 0
            if (st["idle_streak"] >= self.down_after
                    and workers > self.min_workers
                    and now - st["last_change"] >= self.cooldown_s):
                st["idle_streak"] = 0
                st["last_change"] = now
                sm.batcher.set_workers(workers - 1)
                self._decide(sm, "scale_down", workers - 1,
                             refused=0, queue_depth=0)
                return True
        else:
            # neither shedding nor idle: a healthy standing queue — reset
            # both streaks so hysteresis measures CONSECUTIVE evidence
            st["up_streak"] = 0
            st["idle_streak"] = 0
        return False

    def _escalate(self, sm, *, refused: int, queue_depth: int) -> None:
        """Within-mesh capacity is exhausted (worker ceiling, still
        shedding): record that the next lever is ACROSS meshes — a tier
        replica (serve/tier.py) — and tell whoever owns that lever. The
        ordering is deliberate: a worker is a thread over the shared
        (possibly mesh-sharded) AOT cache, free; a replica is a whole new
        mesh worth of chips and compiles, the expensive last resort."""
        self._state[sm.name]["wants_scale_out"] = True
        mesh = getattr(sm.engine, "mesh_axes", None)
        with sm.reload_lock:
            stats = sm.autoscale_stats
            stats["escalations"] = stats.get("escalations", 0) + 1
            stats["wants_scale_out"] = True
            stats["last_decision"] = "escalate"
            stats["last_decision_unix"] = time.time()
        self._events += 1
        log_resilience_event(self.logger, self._events,
                             {"autoscale_escalate": 1.0,
                              "autoscale_workers":
                                  float(sm.batcher.workers),
                              "autoscale_refused_delta": float(refused),
                              "autoscale_queue_depth": float(queue_depth)})
        print(f"[serve-autoscale:{sm.name}] escalate: worker ceiling "
              f"{self.max_workers} reached on mesh "
              f"{mesh or 'single-chip'} and still shedding ({refused} "
              f"requests refused since last sample, queue depth "
              f"{queue_depth}) — next lever is a replica across meshes "
              f"(serve/tier.py); wants_scale_out flagged on /healthz",
              file=sys.stderr, flush=True)
        if self.scale_out is not None:
            try:
                self.scale_out(sm, refused=refused,
                               queue_depth=queue_depth)
            except Exception as e:  # noqa: BLE001 — the hook is advisory;
                # a broken across-mesh lever must not kill the sampler
                print(f"[serve-autoscale:{sm.name}] scale_out hook "
                      f"failed: {e!r}", file=sys.stderr, flush=True)

    def _decide(self, sm, decision: str, workers: int, *,
                refused: int, queue_depth: int) -> None:
        with sm.reload_lock:
            stats = sm.autoscale_stats
            stats[f"{decision}s"] = stats.get(f"{decision}s", 0) + 1
            stats["workers"] = workers
            stats["last_decision"] = decision
            stats["last_decision_unix"] = time.time()
        self._events += 1
        log_resilience_event(self.logger, self._events,
                             {f"autoscale_{decision}": 1.0,
                              "autoscale_workers": float(workers),
                              "autoscale_refused_delta": float(refused),
                              "autoscale_queue_depth": float(queue_depth)})
        print(f"[serve-autoscale:{sm.name}] {decision} -> {workers} "
              f"worker(s) ({refused} requests refused since last sample, "
              f"queue depth {queue_depth}; bounds "
              f"[{self.min_workers},{self.max_workers}])",
              file=sys.stderr, flush=True)
