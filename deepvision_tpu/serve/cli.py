"""`python -m deepvision_tpu.serve` — the serving entrypoint.

Two modes over the same stack (fleet → engines → batchers → metrics →
drain), single-model or multi-model:

    # HTTP serving, one model (POST /predict; SIGTERM drains)
    python -m deepvision_tpu.serve -m resnet50 --workdir runs/resnet50

    # a FLEET: several models behind one process, routed by name
    # (POST /predict/<model>), weights restored per model from the runs
    # root, hot-reloaded when training commits a new verified epoch
    python -m deepvision_tpu.serve -m resnet50,yolov3_digits \
        --runs-root runs --reload-every 10

    # self-driving synthetic load, one JSON summary line, exit 0
    python -m deepvision_tpu.serve -m lenet5 --smoke
    python -m deepvision_tpu.serve -m lenet5,lenet5_digits --smoke

The smoke mode is the `make serve-smoke` / `make serve-fleet-smoke` / CI
surface: it proves the whole path (bucketed AOT compile cache, per-model
coalescing, padding, routing, metrics, graceful drain) end to end without
a client, and SIGTERM mid-smoke exercises the drain contract exactly like
production (docs/SERVING.md).
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from typing import Optional, Sequence

from ..core.resilience import GracefulShutdown


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deepvision_tpu.serve",
        description="Dynamic-batching inference fleet over the model zoo "
                    "(shape-bucketed AOT predict cache, multi-model "
                    "routing, hot weight reload; docs/SERVING.md)")
    p.add_argument("-m", "--model", default=None,
                   help="registered config name, or a comma-separated list "
                        "to serve a fleet (first name is the default model "
                        "bare POST /predict hits; see --list-models)")
    p.add_argument("-c", "--checkpoint", default=None,
                   help="epoch number or 'latest' (needs --workdir; "
                        "single-model only)")
    p.add_argument("--workdir", default=None,
                   help="training workdir to restore weights from (EMA "
                        "weights win when present); single-model only — a "
                        "fleet resolves per-model workdirs under "
                        "--runs-root. Omit both for random-weight smoke "
                        "serving")
    p.add_argument("--runs-root", default=None,
                   help="runs root holding one <runs-root>/<model> workdir "
                        "per served model; models with a restorable "
                        "checkpoint there serve it (and hot-reload from "
                        "it), the rest serve random weights with a warning")
    p.add_argument("--reload-every", type=float, default=0.0,
                   metavar="SECS",
                   help="hot weight reload: poll each model's run dir every "
                        "SECS seconds for new committed epochs; a candidate "
                        "swaps in only after its integrity manifest "
                        "verifies (corrupt candidates are refused and "
                        "logged, old weights keep serving). 0 disables "
                        "(default)")
    p.add_argument("--promote-gate", type=float, default=None,
                   metavar="DELTA",
                   help="accuracy-gated promotion (docs/SERVING.md "
                        "'Promotion'): instead of swapping a verified "
                        "candidate straight in, shadow-eval it against the "
                        "live weights on a pinned shard and promote only if "
                        "the watched metric delta (top-1 / mIoU) is >= "
                        "DELTA (e.g. -0.02 = at most 2 points worse), then "
                        "canary a traffic fraction and auto-roll-back on "
                        "p99/error regression. Decisions land on /healthz "
                        "and the resilience_ stream. Needs --reload-every; "
                        "unset = direct integrity-verified swap (default)")
    p.add_argument("--canary-frac", type=float, default=0.05,
                   metavar="FRAC",
                   help="fraction of live traffic routed to the candidate "
                        "generation during the canary window (default "
                        "0.05; per-generation batches, never mixed)")
    p.add_argument("--canary-window", type=float, default=5.0,
                   metavar="SECS",
                   help="canary decision window: how long candidate and "
                        "baseline traffic are compared (p99, error rate) "
                        "before promote/rollback (default 5)")
    p.add_argument("--flywheel-every", type=float, default=0.0,
                   metavar="SECS",
                   help="drift-triggered continuous training "
                        "(docs/FAILURES.md 'Flywheel decisions'): monitor "
                        "live inputs/outputs against the pinned calibration "
                        "shard every SECS seconds; a confirmed drift "
                        "(consecutive-window hysteresis) fine-tunes a "
                        "bounded candidate and ships it through the "
                        "--promote-gate shadow/canary pipeline, with "
                        "exponential backoff and a retrain circuit on "
                        "repeated failures. Needs --promote-gate. 0 "
                        "disables (default)")
    p.add_argument("--serve-precision", choices=("bf16", "int8"),
                   default="bf16",
                   help="serving precision (docs/SERVING.md 'Quantized "
                        "serving'): int8 calibrates each model on its "
                        "pinned shard, compiles int8 bucket twins beside "
                        "the bf16 cache, and flips the model to int8 ONLY "
                        "if the accuracy gate passes — a regression beyond "
                        "--quant-gate refuses loudly and keeps serving "
                        "bf16 (decision on /healthz + the resilience_ "
                        "stream). Per-request override: body "
                        "{'precision': 'bf16'|'int8'}. Default bf16")
    p.add_argument("--quant-gate", type=float, default=0.02,
                   metavar="DELTA",
                   help="int8 accuracy gate: the watched metric (top-1 / "
                        "mIoU / box-count / PCK) may be at most DELTA "
                        "worse at int8 than bf16 on the pinned shard "
                        "(default 0.02 = 2 points); beyond it the model "
                        "serves bf16 and the refusal is logged")
    p.add_argument("--image-size", type=int, default=None,
                   help="serving resolution (default: each config's)")
    p.add_argument("--model-parallel", type=int, default=1,
                   help="mesh 'model' axis size (shard big params / "
                        "matmuls): the engine places weights under GSPMD "
                        "shardings and AOT-compiles every bucket as one "
                        "sharded program whose outputs gather back to a "
                        "single replicated array, so models bigger than "
                        "one chip's HBM serve across the axis and nothing "
                        "above the engine changes (docs/SERVING.md 'Mesh "
                        "serving'). Leftover devices fill the 'data' axis "
                        "(batch-sharded buckets). Default 1 = single chip")
    p.add_argument("--spatial-parallel", type=int, default=1,
                   help="mesh 'spatial' axis size: shard activations along "
                        "image height (context parallelism; GSPMD "
                        "halo-exchanges the convs) — the lever when the "
                        "RESOLUTION, not the params, exceeds one chip. "
                        "Composes with --model-parallel. Default 1")
    p.add_argument("--hbm-gb", type=float, default=None, metavar="GIB",
                   help="--list-models: annotate each servable config with "
                        "its analytic per-chip weight bytes on the mesh "
                        "the --model-parallel/--spatial-parallel flags "
                        "describe, and whether it fits this per-chip HBM "
                        "budget (GiB) at bf16 and (estimated) int8")
    p.add_argument("--no-verify", action="store_true",
                   help="serve weights whose checkpoint fails (or skips) "
                        "integrity verification — by default a corrupt "
                        "checkpoint REFUSES to serve "
                        "(CheckpointCorruptionError; audit with `python -m "
                        "deepvision_tpu fsck <workdir>`); legacy workdirs "
                        "with no manifests always serve, flagged "
                        "verified:false on /healthz")
    p.add_argument("--buckets", default="1,8,32",
                   help="comma-separated batch buckets compiled at startup "
                        "(max-batch is appended; default 1,8,32)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="coalescing cap = largest bucket (default: largest "
                        "of --buckets)")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="micro-batching deadline: a request waits at most "
                        "this long for batch-mates (p99 floor; default 5)")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="backpressure: per-model pending-example cap before "
                        "submits are rejected with 429 (default 1024)")
    p.add_argument("--workers", type=int, default=1,
                   help="dispatcher workers per model feeding the shared "
                        "AOT bucket cache (default 1; the autoscale floor)")
    p.add_argument("--max-workers", type=int, default=4,
                   help="autoscale ceiling per model (default 4); spawning "
                        "a worker is a thread + a reference — zero "
                        "recompiles (docs/SERVING.md 'Overload control')")
    p.add_argument("--autoscale-every", type=float, default=0.0,
                   metavar="SECS",
                   help="shed-driven autoscaling: sample per-model "
                        "shed/p99/queue signals every SECS seconds and "
                        "scale the dispatcher pool between --workers and "
                        "--max-workers, with hysteresis; every decision on "
                        "/healthz + the resilience_ stream. 0 disables "
                        "(default)")
    p.add_argument("--deadline-ms", type=float, default=10000.0,
                   help="default request deadline (client 'deadline_ms' "
                        "overrides per request): admission control refuses "
                        "at the door (503 + Retry-After) when the queue "
                        "says it is unmeetable, and the result wait "
                        "answers 504 on expiry instead of blocking "
                        "(default 10000 = 10s)")
    p.add_argument("--breaker-k", type=int, default=5,
                   help="circuit breaker: consecutive dispatch errors that "
                        "open a model's circuit (fail-fast 503 naming the "
                        "model; default 5)")
    p.add_argument("--breaker-cooldown", type=float, default=5.0,
                   metavar="SECS",
                   help="circuit breaker: seconds an open circuit waits "
                        "before admitting one half-open probe (default 5)")
    p.add_argument("--trace-sample", type=float, default=None,
                   metavar="FRAC",
                   help="per-request span sampling rate behind GET /trace "
                        "(default: DEEPVISION_TRACE_SAMPLE env or 0.1). "
                        "Requests carrying an explicit X-Request-Id header "
                        "are ALWAYS sampled — the request you are chasing "
                        "leaves its spans (docs/OBSERVABILITY.md)")
    p.add_argument("--no-trace", action="store_true",
                   help="disable span tracing entirely: GET /trace serves "
                        "an empty ring and the request path pays a single "
                        "branch")
    p.add_argument("--drain-grace", type=float, default=0.0,
                   metavar="SECS",
                   help="graceful-drain de-admission window: after SIGTERM "
                        "/healthz flips to 'draining' immediately, then "
                        "the server keeps accepting (and answering) for "
                        "SECS seconds before the batcher drain starts "
                        "refusing work — long enough for a tier router's "
                        "health poll to stop sending first (default 0: "
                        "flip and drain at once; the replica entrypoint "
                        "defaults to 0.75)")
    p.add_argument("--port", type=int, default=8700)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--flush-every", type=float, default=10.0,
                   help="seconds between periodic metric flushes")
    p.add_argument("--smoke", action="store_true",
                   help="drive synthetic in-process load (round-robin over "
                        "the fleet) instead of HTTP; print one JSON summary "
                        "line and exit 0")
    p.add_argument("--duration", type=float, default=2.0,
                   help="--smoke load duration in seconds")
    p.add_argument("--load-threads", type=int, default=8,
                   help="--smoke concurrent synthetic clients")
    p.add_argument("--list-models", action="store_true",
                   help="list servable registered configs — annotated with "
                        "whether a restorable checkpoint exists under "
                        "--runs-root (default runs/), and with per-chip "
                        "weight bytes / HBM-budget fit per precision when "
                        "--hbm-gb or a mesh flag is given — and exit")
    p.add_argument("--compilation-cache",
                   default=os.environ.get("DEEPVISION_COMPILATION_CACHE",
                                          "auto"),
                   metavar="DIR|off",
                   help="persistent XLA compilation cache for the bucket "
                        "compiles (same contract as the training CLI)")
    return p


def restorable_epoch(runs_root: str, name: str) -> Optional[int]:
    """Newest committed checkpoint epoch under `<runs_root>/<name>/ckpt`,
    or None — what `--list-models` annotates and what decides whether a
    fleet member serves trained weights or a random init."""
    from ..core import integrity
    epochs = integrity.committed_epochs(
        os.path.join(runs_root, name, "ckpt"))
    return epochs[-1] if epochs else None


def _build_serve_mesh(args):
    """The serve mesh the --model-parallel/--spatial-parallel flags
    describe, or None for the single-chip default. make_mesh's
    divisibility error (N devices not divisible by model x spatial) is an
    operator mistake, so it surfaces verbatim as the exit message, not a
    stack trace."""
    if args.model_parallel <= 1 and args.spatial_parallel <= 1:
        return None
    from ..parallel.mesh import make_mesh
    try:
        return make_mesh(model_parallel=args.model_parallel,
                         spatial_parallel=args.spatial_parallel)
    except ValueError as e:
        raise SystemExit(f"mesh: {e}")


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB"):
        if n < 1024:
            return f"{n:.0f}{unit}"
        n /= 1024.0
    return f"{n:.2f}GiB"


def _hbm_note(cfg, mesh, hbm_gb: Optional[float]) -> str:
    """Per-chip weight-byte annotation for one servable config: analytic
    bytes under the serve-mesh sharding rule (parallel/mesh — the same
    pure shapes->spec function the engine places with, evaluated over
    `jax.eval_shape` so no weights are ever materialized), int8 estimated
    at the 1.8x byte-cut floor jaxvet's QUANT bar enforces."""
    import jax
    import jax.numpy as jnp

    from ..core.trainer import build_model_from_config
    from ..parallel.mesh import analytic_per_chip_bytes
    model, mcfg = build_model_from_config(cfg)
    sz = mcfg.data.image_size
    S = jax.ShapeDtypeStruct
    shaped = jax.eval_shape(
        lambda r, x: model.init({"params": r,
                                 "dropout": jax.random.fold_in(r, 1)},
                                x, train=True),
        S((2,), jnp.uint32),
        S((2, sz, sz, mcfg.data.channels), jnp.float32))
    bf16 = analytic_per_chip_bytes(shaped, mesh)
    int8 = int(bf16 / 1.8)
    note = (f"per_chip[bf16]={_fmt_bytes(bf16)} "
            f"per_chip[int8]~{_fmt_bytes(int8)}")
    if hbm_gb is not None:
        budget = int(hbm_gb * (1 << 30))
        note += (f" fits[{hbm_gb:g}GiB]="
                 f"bf16:{'yes' if bf16 <= budget else 'NO'}"
                 f"/int8:{'yes' if int8 <= budget else 'NO'}")
    return note


def _list_models(args) -> None:
    """One line per registered config: family, model, servability, and —
    so operators can see what a fleet can ACTUALLY serve — the newest
    restorable checkpoint epoch under the runs root. With --hbm-gb (or a
    mesh flag > 1), each servable line is also annotated with analytic
    per-chip weight bytes on that mesh per precision — which configs FIT
    a chip's HBM budget, before paying any compile."""
    from ..configs import CONFIGS
    root = args.runs_root or "runs"
    want_bytes = (args.hbm_gb is not None or args.model_parallel > 1
                  or args.spatial_parallel > 1)
    mesh = _build_serve_mesh(args) if want_bytes else None
    for name, cfg in CONFIGS.items():
        servable = "-" if cfg.family == "gan" else "yes"
        if cfg.family == "gan":
            ckpt = "-"
        else:
            epoch = restorable_epoch(root, name)
            ckpt = f"epoch {epoch}" if epoch is not None else "-"
        note = ("" if not want_bytes or cfg.family == "gan"
                else " " + _hbm_note(cfg, mesh, args.hbm_gb))
        print(f"{name:24s} family={cfg.family:16s} model={cfg.model:16s} "
              f"servable={servable:3s} ckpt={ckpt}{note}")


def _smoke(server, duration: float, n_threads: int) -> dict:
    """Closed-loop synthetic clients round-robined over the fleet's
    models; SIGTERM drains early and still exits 0 (the production drain
    contract, minus HTTP). Pass requires EVERY served model to have
    answered requests."""
    import numpy as np

    from .batcher import RequestRejected, result_within

    models = list(server.fleet)
    stop = threading.Event()
    errors: list = []

    def client(i: int) -> None:
        sm = models[i % len(models)]   # round robin: all models get load
        rs = np.random.RandomState(i)
        n = 1 + i % min(4, sm.engine.max_batch)  # mixed sizes: buckets
        x = rs.randn(n, *sm.engine.example_shape).astype(
            sm.engine.input_dtype)
        # deadline-bounded wait, same as the HTTP front door: a wedged
        # model fails the smoke with DeadlineExpired in seconds, not a
        # blind 120 s block per client
        deadline_s = sm.batcher.default_deadline_s or 30.0
        while not stop.is_set():
            try:
                result_within(sm.submit(x), deadline_s,
                              what=f"smoke[{sm.name}]")  # promoter-routed
            except RequestRejected:
                return  # drain/overload reached this client — done
            except Exception as e:  # noqa: BLE001 — smoke must report
                errors.append(e)   # (incl. DeadlineExpired: a wedged model
                return             # is a FAILED smoke, loudly and fast)

    with GracefulShutdown(on_signal=stop.set,
                          what="finishing in-flight batches, rejecting new "
                               "work, then exiting 0") as gs:
        server.reloader.start()
        server.autoscaler.start()
        for fw in server.flywheels:
            fw.start()
        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(max(n_threads, len(models)))]
        print(f"[serve:{server.engine.name}] ready: synthetic load "
              f"x{len(threads)} over {server.fleet.names()} for "
              f"{duration:g}s (SIGTERM drains early)", flush=True)
        for t in threads:
            t.start()
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline and not gs.requested:
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        snap = server.drain()
    per_model = server.fleet.snapshots()
    requests_total = sum(s.get("requests", 0) for s in per_model.values())
    starved = [n for n, s in per_model.items() if s.get("requests", 0) == 0]
    ok = not errors and snap.get("requests", 0) > 0 and not starved
    print(json.dumps({
        "serve_smoke": "pass" if ok else "fail",
        "model": server.engine.name,
        "models": {n: {"requests": s.get("requests", 0.0),
                       "weights_epoch": s["weights"]["checkpoint_epoch"],
                       "reloads": server.fleet.get(n).describe()["reload"]
                                  ["reloads"]}
                   for n, s in per_model.items()},
        "requests_total": round(float(requests_total), 1),
        "buckets": list(server.engine.buckets),
        # flywheel-armed smokes (make flywheel-smoke) assert on this
        # section: state machine + episode outcome counters per model
        **({"flywheel": {fw.sm.name: {"state": fw.state, **fw.counters}
                         for fw in server.flywheels}}
           if server.flywheels else {}),
        **{k: round(float(v), 4) for k, v in snap.items()},
    }), flush=True)
    if not ok:
        detail = (f"errors: {errors[:1]!r}" if errors
                  else f"models with zero requests: {starved}" if starved
                  else "no requests completed")
        raise SystemExit(f"serve smoke failed: {detail}")
    return snap


def validate_args(parser: argparse.ArgumentParser, args,
                  require_reload_for_gate: bool = True) -> None:
    """The flag-coupling checks shared by every entrypoint built on
    `build_parser` (serve CLI here, the tier replica in serve/replica.py).
    `require_reload_for_gate=False` relaxes the --promote-gate /
    --reload-every coupling: a tier replica arms the gate but is driven
    through `POST /reload` by the router's rolling promotion instead of
    polling on its own."""
    if not args.model:
        parser.error("-m/--model is required (see --list-models)")
    names = [s.strip() for s in args.model.split(",") if s.strip()]
    if len(set(names)) != len(names):
        parser.error(f"duplicate model names in -m {args.model!r}")
    if len(names) > 1 and args.workdir:
        parser.error("--workdir is single-model; a fleet resolves "
                     "per-model workdirs under --runs-root")
    if len(names) > 1 and args.checkpoint:
        parser.error("-c/--checkpoint is single-model; a fleet serves each "
                     "model's latest verified checkpoint")
    if not 0.0 < args.canary_frac <= 1.0:
        parser.error(f"--canary-frac must be in (0, 1], got "
                     f"{args.canary_frac}")
    if args.canary_window < 0:
        parser.error(f"--canary-window must be >= 0, got "
                     f"{args.canary_window}")
    if (require_reload_for_gate and args.promote_gate is not None
            and not args.reload_every):
        parser.error("--promote-gate needs --reload-every: promotion "
                     "evaluates the candidates the hot-reload poller finds")
    if args.flywheel_every < 0:
        parser.error(f"--flywheel-every must be >= 0, got "
                     f"{args.flywheel_every}")
    if args.flywheel_every and args.promote_gate is None:
        parser.error("--flywheel-every needs --promote-gate: the flywheel "
                     "only ships retrained candidates through the shadow/"
                     "canary promotion pipeline, never a direct swap")
    if args.workers < 1:
        parser.error(f"--workers must be >= 1, got {args.workers}")
    if args.max_workers < args.workers:
        parser.error(f"--max-workers ({args.max_workers}) must be >= "
                     f"--workers ({args.workers})")
    if args.deadline_ms <= 0:
        parser.error(f"--deadline-ms must be > 0, got {args.deadline_ms}")
    if args.breaker_k < 1:
        parser.error(f"--breaker-k must be >= 1, got {args.breaker_k}")
    if args.breaker_cooldown <= 0:
        parser.error(f"--breaker-cooldown must be > 0, got "
                     f"{args.breaker_cooldown}")
    if args.drain_grace < 0:
        parser.error(f"--drain-grace must be >= 0, got {args.drain_grace}")
    if args.trace_sample is not None and not 0.0 <= args.trace_sample <= 1.0:
        parser.error(f"--trace-sample must be in [0, 1], got "
                     f"{args.trace_sample}")
    if args.quant_gate < 0:
        parser.error(f"--quant-gate must be >= 0, got {args.quant_gate}")
    if args.model_parallel < 1:
        parser.error(f"--model-parallel must be >= 1, got "
                     f"{args.model_parallel}")
    if args.spatial_parallel < 1:
        parser.error(f"--spatial-parallel must be >= 1, got "
                     f"{args.spatial_parallel}")


def build_server(args, replica_id: Optional[str] = None):
    """Construct the full serving stack (compile cache -> engines -> fleet
    -> InferenceServer -> optional int8 arm) from parsed `build_parser`
    args. Shared by `main` below and the tier replica entrypoint
    (serve/replica.py), so a replica behind the router is byte-for-byte
    the standalone server."""
    from ..cli import setup_compilation_cache
    setup_compilation_cache(args.compilation_cache)

    from .engine import PredictEngine
    from .fleet import ModelFleet
    from .server import InferenceServer

    names = [s.strip() for s in args.model.split(",") if s.strip()]
    try:
        buckets = tuple(int(b) for b in args.buckets.split(",") if b.strip())
    except ValueError:
        raise SystemExit(f"--buckets must be comma-separated ints, got "
                         f"{args.buckets!r}")

    mesh = _build_serve_mesh(args)
    fleet = ModelFleet()
    for name in names:
        workdir = args.workdir
        if workdir is None and args.runs_root:
            candidate = os.path.join(args.runs_root, name)
            if restorable_epoch(args.runs_root, name) is not None:
                workdir = candidate
            else:
                print(f"[serve:{name}] WARNING: nothing restorable under "
                      f"{candidate!r} — serving RANDOM weights (hot reload "
                      f"stays armed for when training commits there)",
                      flush=True)
                workdir = (candidate if os.path.isdir(candidate)
                           else None)
        engine = PredictEngine.from_config(
            name, workdir=workdir, checkpoint=args.checkpoint,
            image_size=args.image_size, buckets=buckets,
            max_batch=args.max_batch, verify=not args.no_verify,
            mesh=mesh)
        engine.warmup()
        fleet.add(engine, workdir=workdir, max_batch=args.max_batch,
                  max_delay_ms=args.max_delay_ms,
                  max_queue_examples=args.max_queue,
                  workers=args.workers,
                  default_deadline_s=args.deadline_ms / 1000.0,
                  breaker_k=args.breaker_k,
                  breaker_cooldown_s=args.breaker_cooldown)
    server = InferenceServer(
        fleet=fleet, flush_every_s=args.flush_every,
        reload_every_s=args.reload_every,
        log_dir=args.workdir or args.runs_root,
        promote_gate=args.promote_gate,
        canary_frac=args.canary_frac,
        canary_window_s=args.canary_window,
        max_workers=args.max_workers,
        autoscale_every_s=args.autoscale_every,
        flywheel_every_s=args.flywheel_every,
        default_deadline_s=args.deadline_ms / 1000.0,
        trace=not args.no_trace,
        trace_sample=args.trace_sample,
        drain_grace_s=args.drain_grace,
        replica_id=replica_id)
    if args.serve_precision == "int8":
        # arm + gate int8 per model BEFORE traffic: the calibration pass
        # and the bucket compiles are startup cost, never request cost. A
        # refusal (or a family with no predict-side watch metric) keeps
        # that model on bf16 — loudly, never silently; decisions land on
        # the server's resilience_ stream and /healthz.
        from .quantize import arm_int8
        for sm_ in fleet:
            try:
                arm_int8(sm_.engine, gate=args.quant_gate,
                         logger=server.logger)
            except ValueError as e:
                print(f"[serve:{sm_.name}] int8 skipped: {e}", flush=True)
    return server


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.list_models:
        _list_models(args)
        return 0
    validate_args(parser, args)
    server = build_server(args)
    try:
        if args.smoke:
            _smoke(server, args.duration, args.load_threads)
        else:
            server.serve(port=args.port, host=args.host)
    finally:
        server.close()
    return 0
