"""`python -m deepvision_tpu.serve` — the serving entrypoint.

Two modes over the same stack (engine → batcher → metrics → drain):

    # HTTP serving (POST /predict, GET /healthz, GET /stats; SIGTERM drains)
    python -m deepvision_tpu.serve -m resnet50 --workdir runs/resnet50

    # self-driving synthetic load, one JSON summary line, exit 0
    python -m deepvision_tpu.serve -m lenet5 --smoke

The smoke mode is the `make serve-smoke` / CI surface: it proves the whole
path (bucketed AOT compile cache, coalescing, padding, metrics, graceful
drain) end to end without a client, and SIGTERM mid-smoke exercises the
drain contract exactly like production (docs/SERVING.md).
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from typing import Optional, Sequence

from ..core.resilience import GracefulShutdown


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deepvision_tpu.serve",
        description="Dynamic-batching inference server over the model zoo "
                    "(shape-bucketed AOT predict cache; docs/SERVING.md)")
    p.add_argument("-m", "--model", default=None,
                   help="registered config name (see --list-models)")
    p.add_argument("-c", "--checkpoint", default=None,
                   help="epoch number or 'latest' (needs --workdir)")
    p.add_argument("--workdir", default=None,
                   help="training workdir to restore weights from (EMA "
                        "weights win when present); omit for random-weight "
                        "smoke serving")
    p.add_argument("--image-size", type=int, default=None,
                   help="serving resolution (default: the config's)")
    p.add_argument("--no-verify", action="store_true",
                   help="serve weights whose checkpoint fails (or skips) "
                        "integrity verification — by default a corrupt "
                        "checkpoint REFUSES to serve "
                        "(CheckpointCorruptionError; audit with `python -m "
                        "deepvision_tpu fsck <workdir>`); legacy workdirs "
                        "with no manifests always serve, flagged "
                        "verified:false on /healthz")
    p.add_argument("--buckets", default="1,8,32",
                   help="comma-separated batch buckets compiled at startup "
                        "(max-batch is appended; default 1,8,32)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="coalescing cap = largest bucket (default: largest "
                        "of --buckets)")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="micro-batching deadline: a request waits at most "
                        "this long for batch-mates (p99 floor; default 5)")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="backpressure: pending-example cap before submits "
                        "are rejected with 429 (default 1024)")
    p.add_argument("--port", type=int, default=8700)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--flush-every", type=float, default=10.0,
                   help="seconds between periodic metric flushes")
    p.add_argument("--smoke", action="store_true",
                   help="drive synthetic in-process load instead of HTTP; "
                        "print one JSON summary line and exit 0")
    p.add_argument("--duration", type=float, default=2.0,
                   help="--smoke load duration in seconds")
    p.add_argument("--load-threads", type=int, default=8,
                   help="--smoke concurrent synthetic clients")
    p.add_argument("--list-models", action="store_true",
                   help="list servable registered configs and exit")
    p.add_argument("--compilation-cache",
                   default=os.environ.get("DEEPVISION_COMPILATION_CACHE",
                                          "auto"),
                   metavar="DIR|off",
                   help="persistent XLA compilation cache for the bucket "
                        "compiles (same contract as the training CLI)")
    return p


def _list_models() -> None:
    from ..configs import CONFIGS
    for name, cfg in CONFIGS.items():
        servable = "-" if cfg.family == "gan" else "yes"
        print(f"{name:24s} family={cfg.family:16s} model={cfg.model:16s} "
              f"servable={servable}")


def _smoke(server, duration: float, n_threads: int) -> dict:
    """Closed-loop synthetic clients through the batcher; SIGTERM drains
    early and still exits 0 (the production drain contract, minus HTTP)."""
    import numpy as np

    from .batcher import RequestRejected

    eng = server.engine
    stop = threading.Event()
    errors: list = []

    def client(i: int) -> None:
        rs = np.random.RandomState(i)
        n = 1 + i % min(4, eng.max_batch)  # mixed sizes: exercise buckets
        x = rs.randn(n, *eng.example_shape).astype(eng.input_dtype)
        while not stop.is_set():
            try:
                server.batcher.submit(x).result(timeout=120)
            except RequestRejected:
                return  # drain/overload reached this client — done
            except Exception as e:  # noqa: BLE001 — smoke must report
                errors.append(e)
                return

    with GracefulShutdown(on_signal=stop.set,
                          what="finishing in-flight batches, rejecting new "
                               "work, then exiting 0") as gs:
        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(n_threads)]
        print(f"[serve:{eng.name}] ready: synthetic load x{n_threads} for "
              f"{duration:g}s (SIGTERM drains early)", flush=True)
        for t in threads:
            t.start()
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline and not gs.requested:
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        snap = server.drain()
    ok = not errors and snap.get("requests", 0) > 0
    print(json.dumps({
        "serve_smoke": "pass" if ok else "fail",
        "model": eng.name,
        "buckets": list(eng.buckets),
        **{k: round(float(v), 4) for k, v in snap.items()},
    }), flush=True)
    if not ok:
        raise SystemExit(f"serve smoke failed: {errors[:1]!r}" if errors
                         else "serve smoke failed: no requests completed")
    return snap


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.list_models:
        _list_models()
        return 0
    if not args.model:
        parser.error("-m/--model is required (see --list-models)")

    from ..cli import setup_compilation_cache
    setup_compilation_cache(args.compilation_cache)

    from .engine import PredictEngine
    from .server import InferenceServer

    try:
        buckets = tuple(int(b) for b in args.buckets.split(",") if b.strip())
    except ValueError:
        raise SystemExit(f"--buckets must be comma-separated ints, got "
                         f"{args.buckets!r}")
    engine = PredictEngine.from_config(
        args.model, workdir=args.workdir, checkpoint=args.checkpoint,
        image_size=args.image_size, buckets=buckets,
        max_batch=args.max_batch, verify=not args.no_verify)
    engine.warmup()
    server = InferenceServer(
        engine, max_delay_ms=args.max_delay_ms,
        max_queue_examples=args.max_queue, workdir=args.workdir,
        flush_every_s=args.flush_every)
    try:
        if args.smoke:
            _smoke(server, args.duration, args.load_threads)
        else:
            server.serve(port=args.port, host=args.host)
    finally:
        server.close()
    return 0
