"""Dynamic micro-batching: coalesce concurrent requests into one dispatch.

The engine (serve/engine.py) kills retrace and per-shape compile; this
module kills batch-of-1 utilization. Concurrent `submit()` calls land in a
thread-safe queue; a POOL of dispatcher workers (1 by default) coalesces
them up to `max_batch` examples or until the OLDEST request's
`max_delay_ms` deadline expires — whichever comes first — pads to the
nearest bucket, runs one device dispatch, and scatters the per-request
output slices back through `concurrent.futures.Future`s. Every request
lives in exactly ONE batch, so row ownership is worker-count-independent;
workers share the engine's AOT bucket cache, so `set_workers()` is a
thread + a reference — ZERO recompiles (the autoscaler's whole premise,
serve/autoscale.py). With one worker the device idles while the worker
waits out the coalescing deadline; extra workers overlap collect with
dispatch and, on multi-core hosts, overlap the host-side batch work too.

Overload control at the door (`submit` refuses BEFORE accepting — nothing
partial ever happens):

- `Overloaded` (HTTP 429): example-counted backpressure — once
  `max_queue_examples` are pending, shed instead of building an unbounded
  latency queue.
- `DeadlineUnmeetable` (HTTP 503 + Retry-After): requests carry a deadline
  (client-supplied or the configured default); when the dispatch-time EMA
  x queued batches says the answer cannot arrive in time, refuse NOW — a
  fast 503 the client can retry elsewhere beats a slow 504 here.
- `CircuitOpen` (HTTP 503 naming the model): the per-model circuit breaker
  (serve/autoscale.CircuitBreaker) is open after K consecutive dispatch
  errors — fail fast until the half-open probe proves the path again.
- `Draining` (HTTP 503): shutting down; in-flight batches finish.

`result_within()` is the deadline-bounded wait every caller of a submit
future uses (the HTTP handler, `--smoke`, the benches): a wedged dispatch
answers `DeadlineExpired` (HTTP 504) in bounded time instead of blocking a
blind 120 s.
"""

from __future__ import annotations

import math
import queue
import sys
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import List, Optional

import numpy as np

from ..core.resilience import log_resilience_event
from ..utils.faults import FaultInjector
from .engine import PredictEngine, pick_bucket, tree_slice


class RequestRejected(RuntimeError):
    """Base: the request was NOT accepted — nothing partial happened."""


class Overloaded(RequestRejected):
    """Pending examples >= max_queue_examples — shed load upstream (429)."""


class Draining(RequestRejected):
    """Shutting down: in-flight batches finish, new work is rejected (503)."""


class DeadlineUnmeetable(RequestRejected):
    """Admission control refused at the door: the dispatch-time EMA x
    queued batches says the result cannot arrive inside the request's
    deadline (HTTP 503 + Retry-After `retry_after_s`) — shed NOW so the
    client retries another replica instead of waiting for a certain 504."""

    def __init__(self, msg: str, *, eta_s: float, deadline_s: float,
                 retry_after_s: float):
        super().__init__(msg)
        self.eta_s = eta_s
        self.deadline_s = deadline_s
        self.retry_after_s = retry_after_s


class CircuitOpen(RequestRejected):
    """The model's circuit breaker is open (K consecutive dispatch errors):
    fail fast with the model's name (HTTP 503) until the half-open probe
    closes it — see serve/autoscale.CircuitBreaker."""

    def __init__(self, msg: str, *, model: str, retry_after_s: float):
        super().__init__(msg)
        self.model = model
        self.retry_after_s = retry_after_s


class DeadlineExpired(TimeoutError):
    """An ACCEPTED request's result did not arrive by its deadline (HTTP
    504). Distinct from RequestRejected: the work may still complete on
    the device — only the waiter gave up."""


def result_within(future: Future, deadline_s: Optional[float], *,
                  what: str = "request"):
    """Deadline-bounded `future.result()`: raises `DeadlineExpired` after
    `deadline_s` (None = wait forever — explicit opt-in, never a default).
    The single wait primitive for the HTTP handler, `--smoke`, and the
    benches, so no caller can reintroduce a blind unbounded block."""
    try:
        return future.result(timeout=deadline_s)
    except _FutureTimeout:
        raise DeadlineExpired(
            f"{what} deadline of {deadline_s:g}s expired before a result "
            f"arrived — the model is wedged or the queue estimate was "
            f"optimistic; retry with a longer deadline or another replica"
        ) from None


class _Request:
    __slots__ = ("images", "n", "future", "t_submit", "generation",
                 "precision", "trace")

    def __init__(self, images: np.ndarray,
                 generation: Optional[str] = None,
                 precision: Optional[str] = None,
                 trace=None):
        self.images = images
        self.n = images.shape[0]
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        # weight generation this request is pinned to (None = live). The
        # dispatcher never coalesces requests of different generations into
        # one batch — the promotion canary's zero-mixed-weights contract.
        self.generation = generation
        # compiled precision this request is pinned to (None = the model's
        # active precision). Same coalescing rule as generations: a batch
        # runs ONE precision's executables — int8 and bf16 rows never mix.
        self.precision = precision
        # obs.trace.TraceContext of a SAMPLED request (None for unsampled /
        # tracing off): the dispatcher records this request's queue_wait
        # span and links it to the batch span that served it
        self.trace = trace


# queue control tokens: None stops ALL workers (drain, re-put by each
# exiting worker so siblings see it too); _RETIRE stops exactly one
# SUPERNUMERARY worker (scale-down — a worker that pops it while the pool
# is already at target drops it, so a stale token can never shrink below
# the current target)
_RETIRE = object()


def _settle(fut: Future, result=None, exc: Optional[BaseException] = None):
    """Deliver ignoring client-side cancellation races."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
    except InvalidStateError:
        pass  # client cancelled/abandoned the future — nothing to deliver


class DynamicBatcher:
    """Thread-safe request queue + a pool of dispatcher workers over an
    engine.

    `submit(images) -> Future` accepts `(n, *example_shape)` with
    `1 <= n <= max_batch` (or one bare example); the future resolves to the
    output pytree sliced to exactly those n rows, in order. `workers` sizes
    the initial pool; `set_workers()` grows/shrinks it live (the
    autoscaler's lever — zero recompiles, the workers share the engine's
    AOT bucket cache). `default_deadline_s` arms admission control for
    submits that don't carry their own deadline (None = no default, every
    request admitted regardless of the queue).
    """

    def __init__(self, engine: PredictEngine, *,
                 max_batch: Optional[int] = None,
                 max_delay_ms: float = 5.0,
                 max_queue_examples: int = 1024,
                 metrics=None,
                 workers: int = 1,
                 default_deadline_s: Optional[float] = None,
                 faults: Optional[FaultInjector] = None):
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.engine = engine
        self.max_batch = min(int(max_batch or engine.max_batch),
                             engine.max_batch)
        self.max_delay = max_delay_ms / 1000.0
        self.max_queue_examples = int(max_queue_examples)
        self.metrics = metrics
        self.default_deadline_s = default_deadline_s
        # per-model circuit breaker (serve/autoscale.CircuitBreaker),
        # attached by fleet.add: submit fail-fasts while it is open, and
        # every dispatch outcome is recorded on it. None = no breaker
        # (bare library use).
        self.breaker = None
        # resilience_ event stream for the observer-tap error log (set by
        # the server; None = stderr only)
        self.logger = None
        # obs.trace.Tracer (set by the server / the benches; None = tracing
        # off — the dispatch path pays exactly one attribute check). Batch
        # spans (bucket/generation/worker) are recorded whenever enabled;
        # per-request queue_wait spans only for sampled requests.
        self.tracer = None
        self.faults = faults if faults is not None else FaultInjector.from_env()
        # optional per-batch tap `observer(generation, latencies_s,
        # dispatch_s, error, sample=None)` — the promotion controller's
        # canary-vs-baseline comparison feed and the flywheel drift
        # monitor's live-sample source (generation is 'live' or
        # 'candidate'; dispatch_s is the device-dispatch wall time, the
        # part of latency wholly owned by ONE generation; error is the
        # dispatch exception or None; `sample` is a dict carrying
        # REFERENCES — never copies — to the dispatched batch:
        # {'images': <(n, *example_shape) input array>, 'outputs': <engine
        # output pytree, None on a failed dispatch>, 'trace_ref':
        # 'span:<id>' or None}. Observers that retain anything must sample/
        # copy on their side — the reservoir in flywheel/drift.py does).
        # Called from a dispatcher worker; an observer exception is counted
        # on the metrics and logged once per distinct error (never silently
        # swallowed).
        self.observer = None
        self._observer_errors_seen: set = set()
        self._observer_error_seq = 0
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._pending = 0          # examples accepted, results not yet set
        self._draining = False
        # EMA of per-batch device dispatch wall time — the admission
        # controller's service-time estimate (0 until the first dispatch:
        # no evidence, every deadline admitted)
        self._dispatch_ema_s = 0.0
        self._threads: List[threading.Thread] = []
        self._target_workers = int(workers)
        self._worker_seq = 0
        for _ in range(self._target_workers):
            self._spawn_locked()

    @property
    def queue_depth(self) -> int:
        """Examples accepted whose results are not yet delivered (queued +
        in in-flight dispatches) — the serving analog of the prefetcher's
        queue_depth stall diagnostic."""
        with self._lock:
            return self._pending

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    @property
    def dispatch_ema_s(self) -> float:
        with self._lock:
            return self._dispatch_ema_s

    # -- worker pool -------------------------------------------------------

    @property
    def workers(self) -> int:
        with self._lock:
            return len(self._threads)

    def _spawn_locked(self) -> None:
        self._worker_seq += 1
        t = threading.Thread(
            target=self._loop, daemon=True,
            name=f"dispatch-worker-{getattr(self.engine, 'name', 'model')}"
                 f"-{self._worker_seq}")
        self._threads.append(t)
        t.start()

    def set_workers(self, n: int) -> int:
        """Resize the dispatcher pool to n workers (>= 1). Growing spawns
        threads immediately; shrinking enqueues retire tokens that each
        stop one worker at a batch boundary — no in-flight batch is ever
        abandoned. Returns the new target. A draining batcher refuses to
        resize (its workers are already exiting)."""
        n = max(1, int(n))
        retire = 0
        with self._lock:
            if self._draining:
                return len(self._threads)
            self._target_workers = n
            while len(self._threads) < n:
                self._spawn_locked()
            retire = len(self._threads) - n
        for _ in range(retire):
            self._q.put(_RETIRE)
        return n

    # -- client side -------------------------------------------------------

    def submit(self, images, *, generation: Optional[str] = None,
               precision: Optional[str] = None,
               deadline_s: Optional[float] = None, trace=None) -> Future:
        x = self.engine._coerce(images)
        n = x.shape[0]
        if n > self.max_batch:
            raise ValueError(
                f"request of {n} examples exceeds max_batch="
                f"{self.max_batch}; split client batches")
        if precision is not None:
            # refuse an unarmed precision AT THE DOOR (400, not a batch of
            # doomed futures); None resolves at dispatch time instead
            self.engine._resolve_precision(precision)
        breaker = self.breaker
        if breaker is not None:
            wait_s = breaker.reject_for()
            if wait_s is not None:
                if self.metrics is not None:
                    self.metrics.observe_breaker_reject()
                raise CircuitOpen(
                    f"circuit open for model {breaker.name!r} after "
                    f"{breaker.k} consecutive dispatch errors — failing "
                    f"fast; half-open probe in {wait_s:.2f}s",
                    model=breaker.name, retry_after_s=wait_s)
        dl = deadline_s if deadline_s is not None else self.default_deadline_s
        with self._lock:
            if self._draining:
                raise Draining(
                    "server is draining: in-flight batches are finishing, "
                    "new work is rejected — retry against another replica")
            if self._pending + n > self.max_queue_examples:
                if self.metrics is not None:
                    self.metrics.observe_shed(1)  # the shed-rate side of the
                    # load contract: rejected work must be counted where it
                    # was rejected, not inferred by the client
                raise Overloaded(
                    f"queue full ({self._pending} examples pending, cap "
                    f"{self.max_queue_examples}) — shed load or raise "
                    f"max_queue_examples")
            if dl is not None:
                eta = self._eta_locked(n)
                if eta > dl:
                    # Retry-After ~= time for the current backlog to clear
                    retry = max(0.001, eta - self.max_delay
                                - self._dispatch_ema_s)
                    if self.metrics is not None:
                        self.metrics.observe_admission_reject()
                    raise DeadlineUnmeetable(
                        f"deadline {dl * 1000:g}ms unmeetable: estimated "
                        f"completion in {eta * 1000:.1f}ms "
                        f"({self._pending} examples queued, dispatch EMA "
                        f"{self._dispatch_ema_s * 1000:.1f}ms x "
                        f"{len(self._threads)} worker(s)) — refused at the "
                        f"door so you can retry elsewhere",
                        eta_s=eta, deadline_s=dl, retry_after_s=retry)
            self._pending += n
        req = _Request(x, generation=generation, precision=precision,
                       trace=trace)
        self._q.put(req)
        return req.future

    def _eta_locked(self, n: int) -> float:
        """Expected submit->result time for an n-example request arriving
        NOW: the coalescing wait plus (batches ahead of and including it)
        x dispatch EMA, divided across the worker pool. Deliberately a
        first-order model — admission control only needs to be right about
        order of magnitude to turn a certain 504 into a fast 503 — and
        deliberately optimistic when there is no dispatch evidence yet
        (EMA 0 admits everything: never refuse on zero data)."""
        ema = self._dispatch_ema_s
        if ema <= 0.0:
            return 0.0
        batches_ahead = math.ceil((self._pending + n) / self.max_batch)
        workers = max(1, len(self._threads))
        return self.max_delay + ema * (batches_ahead / workers)

    # -- dispatcher workers ------------------------------------------------

    def _loop(self) -> None:
        carry: Optional[_Request] = None   # overflow of this worker's last
        while True:                        # batch (per-worker, not shared)
            first = carry
            carry = None
            if first is None:
                first = self._q.get()       # idle: block until work or stop
            if first is None:               # stop: everything accepted
                self._q.put(None)           # before the sentinel has been
                break                       # dispatched; re-put for siblings
            if first is _RETIRE:            # scale-down token: stop exactly
                with self._lock:            # one supernumerary worker
                    if len(self._threads) > self._target_workers:
                        self._threads.remove(threading.current_thread())
                        return
                continue                    # stale token (target re-raised)
            t_collect = time.monotonic()   # batch-formation start (the
            batch: List[_Request] = [first]  # batch span's left edge)
            total = first.n
            deadline = first.t_submit + self.max_delay
            while total < self.max_batch:
                # Past the deadline, requests ALREADY queued still coalesce
                # (get_nowait) — only waiting for future arrivals stops.
                # Blocking-only here is the classic micro-batcher bug: under
                # backlog the oldest request is always past its deadline, so
                # every batch degenerates to size 1 exactly when batching
                # matters most.
                wait = deadline - time.monotonic()
                try:
                    nxt = (self._q.get(timeout=wait) if wait > 0
                           else self._q.get_nowait())
                except queue.Empty:
                    break                   # deadline flush
                if nxt is None or nxt is _RETIRE:
                    self._q.put(nxt)        # control token mid-collect:
                    break                   # hand it back, flush this batch
                if total + nxt.n > self.max_batch:
                    carry = nxt             # first request of the NEXT batch
                    break                   # max_batch flush
                if nxt.generation != first.generation \
                        or nxt.precision != first.precision:
                    carry = nxt             # generation/precision boundary:
                    break                   # a batch runs ONE weight set
                batch.append(nxt)
                total += nxt.n
            self._dispatch(batch, total, t_collect)

    def _record_dispatch_locked(self, dt: float) -> None:
        self._dispatch_ema_s = (dt if self._dispatch_ema_s <= 0.0
                                else 0.2 * dt + 0.8 * self._dispatch_ema_s)

    def _dispatch(self, batch: List[_Request], total: int,
                  t_collect: Optional[float] = None) -> None:
        images = (batch[0].images if len(batch) == 1
                  else np.concatenate([r.images for r in batch]))
        generation = batch[0].generation   # whole batch shares both (the
        precision = batch[0].precision     # collect loop breaks on either
        t0 = time.monotonic()              # boundary)
        # the precision label metrics/spans carry: an explicit request
        # precision, else the model's active one at dispatch time
        precision_label = precision or getattr(self.engine, "precision",
                                               "bf16")
        try:
            self.faults.before_serve_dispatch()
            out = self.engine.predict(images, generation=generation,
                                      precision=precision)
        except BaseException as e:  # noqa: BLE001 — must reach the futures,
            now = time.monotonic()  # not kill the dispatcher worker
            with self._lock:
                self._pending -= total
                self._record_dispatch_locked(now - t0)
            if self.metrics is not None:
                self.metrics.observe_dispatch_error()
            trace_ref = self._trace_batch(batch, total, t_collect, t0, now,
                                          generation, precision_label,
                                          error=repr(e))
            if self.breaker is not None:
                # the failing batch's span is the breaker's evidence: a
                # later breaker_opened event joins back to these spans
                self.breaker.record(ok=False, trace_ref=trace_ref)
            for r in batch:
                _settle(r.future, exc=e)
            self._observe(generation, [now - r.t_submit for r in batch],
                          now - t0, e, trace_ref=trace_ref, images=images)
            return
        now = time.monotonic()
        with self._lock:
            self._pending -= total
            self._record_dispatch_locked(now - t0)
        if self.breaker is not None:
            self.breaker.record(ok=True)
        lo = 0
        for r in batch:
            _settle(r.future, tree_slice(out, lo, lo + r.n))
            lo += r.n
        latencies = [now - r.t_submit for r in batch]
        if self.metrics is not None:
            self.metrics.observe_batch(
                n_real=total,
                bucket=pick_bucket(total, self.engine.buckets),
                dispatch_s=now - t0,
                request_latencies_s=latencies,
                # queueing vs device split: submit accept -> dispatch start
                queue_waits_s=[t0 - r.t_submit for r in batch],
                precision=precision_label)
        trace_ref = self._trace_batch(batch, total, t_collect, t0, now,
                                      generation, precision_label)
        self._observe(generation, latencies, now - t0, None,
                      trace_ref=trace_ref, images=images, outputs=out)

    def _trace_batch(self, batch: List[_Request], total: int,
                     t_collect: Optional[float], t0: float, now: float,
                     generation: Optional[str], precision: str = "bf16",
                     error: Optional[str] = None) -> Optional[str]:
        """Record the batch-level spans (one `batch` span linked to its N
        request spans, plus the `device_dispatch` child) and each sampled
        member's `queue_wait` span. Returns a ``span:<id>`` trace ref for
        the resilience events this dispatch may trigger, or None when
        tracing is off — the whole method is behind ONE branch."""
        tr = self.tracer
        if tr is None or not tr.enabled:
            return None
        name = getattr(self.engine, "name", "model")
        worker = threading.current_thread().name
        bid = tr.new_id()
        traced = [r for r in batch if r.trace is not None]
        for r in traced:
            tr.add("queue_wait", "serve", int(r.t_submit * 1e9),
                   int((t0 - r.t_submit) * 1e9),
                   args={"request_id": r.trace.request_id, "batch": bid,
                         "model": name}, tid=worker)
        args = {"model": name,
                "bucket": pick_bucket(total, self.engine.buckets),
                "generation": generation or "live", "worker": worker,
                "precision": precision,
                "n_real": total, "n_requests": len(batch),
                "requests": [r.trace.request_id for r in traced]}
        if error is not None:
            args["error"] = error
        t_batch = t_collect if t_collect is not None else batch[0].t_submit
        tr.add("batch", "serve", int(t_batch * 1e9),
               int((now - t_batch) * 1e9), args=args, span_id=bid,
               tid=worker)
        tr.add("device_dispatch", "serve", int(t0 * 1e9),
               int((now - t0) * 1e9),
               args={"model": name, "batch": bid,
                     "generation": generation or "live"}, tid=worker)
        return f"span:{bid}"

    def _observe(self, generation, latencies, dispatch_s, error,
                 trace_ref: Optional[str] = None,
                 images=None, outputs=None) -> None:
        observer = self.observer
        if observer is None:
            return
        # references only, assembled AFTER every future is settled: a slow
        # (or broken) tap can never delay or damage a client's result
        sample = None
        if images is not None:
            sample = {"images": images, "outputs": outputs,
                      "trace_ref": trace_ref}
        try:
            observer(generation or "live", latencies, dispatch_s, error,
                     sample=sample)
        except Exception as e:  # noqa: BLE001 — a broken tap must not take
            # the dispatcher worker (and every future) with it, but it must
            # also never be SILENT: count it, and log one resilience event
            # per distinct error so a broken canary feed is an incident
            # line, not a mystery
            if self.metrics is not None:
                self.metrics.observe_observer_error()
            key = (type(e).__name__, str(e))
            with self._lock:
                fresh = key not in self._observer_errors_seen
                if fresh:
                    self._observer_errors_seen.add(key)
                    self._observer_error_seq += 1
                    seq = self._observer_error_seq
            if fresh:
                log_resilience_event(self.logger, seq,
                                     {"serve_observer_error": 1.0},
                                     trace_ref=trace_ref)
                print(f"[serve:{getattr(self.engine, 'name', 'model')}] "
                      f"batch observer raised {type(e).__name__}: {e} "
                      f"(suppressed; counted on metrics, further repeats "
                      f"silent)",
                      file=sys.stderr, flush=True)

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Reject new work, finish everything already accepted, stop every
        dispatcher worker. Idempotent. True once all workers have exited."""
        with self._lock:
            self._draining = True
            threads = list(self._threads)
        self._q.put(None)
        deadline = None if timeout is None else time.monotonic() + timeout
        ok = True
        for t in threads:
            t.join(None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
            ok = ok and not t.is_alive()
        return ok

    close = drain
