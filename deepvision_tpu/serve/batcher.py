"""Dynamic micro-batching: coalesce concurrent requests into one dispatch.

The engine (serve/engine.py) kills retrace and per-shape compile; this
module kills batch-of-1 utilization. Concurrent `submit()` calls land in a
thread-safe queue; a single dispatcher thread coalesces them up to
`max_batch` examples or until the OLDEST request's `max_delay_ms` deadline
expires — whichever comes first — pads to the nearest bucket, runs one
device dispatch, and scatters the per-request output slices back through
`concurrent.futures.Future`s. One device program in flight at a time, by
construction: the device is the serialization point anyway, and a single
dispatcher keeps the queue discipline (and the latency accounting) exact.

Backpressure is example-counted: once `max_queue_examples` are pending
(queued + in the in-flight dispatch), `submit` raises `Overloaded` — load
sheds at the door (HTTP 429) instead of building an unbounded latency queue.
`drain()` is the graceful-shutdown half (used by serve/server.py under the
resilience SIGTERM contract): new work is rejected with `Draining` (503),
everything already accepted finishes, the dispatcher thread exits.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import List, Optional

import numpy as np

from .engine import PredictEngine, pick_bucket, tree_slice


class RequestRejected(RuntimeError):
    """Base: the request was NOT accepted — nothing partial happened."""


class Overloaded(RequestRejected):
    """Pending examples >= max_queue_examples — shed load upstream (429)."""


class Draining(RequestRejected):
    """Shutting down: in-flight batches finish, new work is rejected (503)."""


class _Request:
    __slots__ = ("images", "n", "future", "t_submit", "generation")

    def __init__(self, images: np.ndarray,
                 generation: Optional[str] = None):
        self.images = images
        self.n = images.shape[0]
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        # weight generation this request is pinned to (None = live). The
        # dispatcher never coalesces requests of different generations into
        # one batch — the promotion canary's zero-mixed-weights contract.
        self.generation = generation


def _settle(fut: Future, result=None, exc: Optional[BaseException] = None):
    """Deliver ignoring client-side cancellation races."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
    except InvalidStateError:
        pass  # client cancelled/abandoned the future — nothing to deliver


class DynamicBatcher:
    """Thread-safe request queue + single dispatcher thread over an engine.

    `submit(images) -> Future` accepts `(n, *example_shape)` with
    `1 <= n <= max_batch` (or one bare example); the future resolves to the
    output pytree sliced to exactly those n rows, in order.
    """

    def __init__(self, engine: PredictEngine, *,
                 max_batch: Optional[int] = None,
                 max_delay_ms: float = 5.0,
                 max_queue_examples: int = 1024,
                 metrics=None):
        if max_delay_ms < 0:
            raise ValueError(f"max_delay_ms must be >= 0, got {max_delay_ms}")
        self.engine = engine
        self.max_batch = min(int(max_batch or engine.max_batch),
                             engine.max_batch)
        self.max_delay = max_delay_ms / 1000.0
        self.max_queue_examples = int(max_queue_examples)
        self.metrics = metrics
        # optional per-batch tap `observer(generation, latencies_s,
        # dispatch_s, error)` — the promotion controller's
        # canary-vs-baseline comparison feed (generation is 'live' or
        # 'candidate'; dispatch_s is the device-dispatch wall time, the
        # part of latency wholly owned by ONE generation; error is the
        # dispatch exception or None). Called from the dispatcher thread.
        self.observer = None
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._pending = 0          # examples accepted, results not yet set
        self._draining = False
        self._carry: Optional[_Request] = None  # overflow of the last batch
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dynamic-batcher")
        self._thread.start()

    @property
    def queue_depth(self) -> int:
        """Examples accepted whose results are not yet delivered (queued +
        in the in-flight dispatch) — the serving analog of the prefetcher's
        queue_depth stall diagnostic."""
        with self._lock:
            return self._pending

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    # -- client side -------------------------------------------------------

    def submit(self, images, *, generation: Optional[str] = None) -> Future:
        x = self.engine._coerce(images)
        n = x.shape[0]
        if n > self.max_batch:
            raise ValueError(
                f"request of {n} examples exceeds max_batch="
                f"{self.max_batch}; split client batches")
        with self._lock:
            if self._draining:
                raise Draining(
                    "server is draining: in-flight batches are finishing, "
                    "new work is rejected — retry against another replica")
            if self._pending + n > self.max_queue_examples:
                if self.metrics is not None:
                    self.metrics.observe_shed(1)  # the shed-rate side of the
                    # load contract: rejected work must be counted where it
                    # was rejected, not inferred by the client
                raise Overloaded(
                    f"queue full ({self._pending} examples pending, cap "
                    f"{self.max_queue_examples}) — shed load or raise "
                    f"max_queue_examples")
            self._pending += n
        req = _Request(x, generation=generation)
        self._q.put(req)
        return req.future

    # -- dispatcher --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            first = self._carry
            self._carry = None
            if first is None:
                first = self._q.get()       # idle: block until work or stop
            if first is None:               # stop sentinel (queue is FIFO:
                break                       # everything accepted before it
                                            # has already been dispatched)
            batch: List[_Request] = [first]
            total = first.n
            deadline = first.t_submit + self.max_delay
            while total < self.max_batch:
                # Past the deadline, requests ALREADY queued still coalesce
                # (get_nowait) — only waiting for future arrivals stops.
                # Blocking-only here is the classic micro-batcher bug: under
                # backlog the oldest request is always past its deadline, so
                # every batch degenerates to size 1 exactly when batching
                # matters most.
                wait = deadline - time.monotonic()
                try:
                    nxt = (self._q.get(timeout=wait) if wait > 0
                           else self._q.get_nowait())
                except queue.Empty:
                    break                   # deadline flush
                if nxt is None:             # stop observed mid-collect:
                    self._q.put(None)       # finish this batch, then exit
                    break
                if total + nxt.n > self.max_batch:
                    self._carry = nxt       # first request of the NEXT batch
                    break                   # max_batch flush
                if nxt.generation != first.generation:
                    self._carry = nxt       # generation boundary: a batch
                    break                   # runs ONE weight generation
                batch.append(nxt)
                total += nxt.n
            self._dispatch(batch, total)

    def _dispatch(self, batch: List[_Request], total: int) -> None:
        images = (batch[0].images if len(batch) == 1
                  else np.concatenate([r.images for r in batch]))
        generation = batch[0].generation   # whole batch shares it (collect
        t0 = time.monotonic()              # loop breaks on a boundary)
        try:
            out = self.engine.predict(images, generation=generation)
        except BaseException as e:  # noqa: BLE001 — must reach the futures,
            with self._lock:        # not kill the dispatcher thread
                self._pending -= total
            now = time.monotonic()
            for r in batch:
                _settle(r.future, exc=e)
            self._observe(generation, [now - r.t_submit for r in batch],
                          now - t0, e)
            return
        now = time.monotonic()
        with self._lock:
            self._pending -= total
        lo = 0
        for r in batch:
            _settle(r.future, tree_slice(out, lo, lo + r.n))
            lo += r.n
        latencies = [now - r.t_submit for r in batch]
        if self.metrics is not None:
            self.metrics.observe_batch(
                n_real=total,
                bucket=pick_bucket(total, self.engine.buckets),
                dispatch_s=now - t0,
                request_latencies_s=latencies)
        self._observe(generation, latencies, now - t0, None)

    def _observe(self, generation, latencies, dispatch_s, error) -> None:
        observer = self.observer
        if observer is None:
            return
        try:
            observer(generation or "live", latencies, dispatch_s, error)
        except Exception:  # noqa: BLE001 — a broken tap must not take the
            pass           # dispatcher thread (and every future) with it

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Reject new work, finish everything already accepted, stop the
        dispatcher thread. Idempotent. True once the thread has exited."""
        with self._lock:
            self._draining = True
        self._q.put(None)
        self._thread.join(timeout)
        return not self._thread.is_alive()

    close = drain
