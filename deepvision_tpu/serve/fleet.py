"""Multi-model serving fleet: many engines, one process, one front door.

PR 3's stack serves exactly one model per process; the reference zoo
registers 13 model families, and a real deployment serves several at once.
`ModelFleet` is the registry-shaped layer between the HTTP front-end and
the engines: each served model gets its OWN `DynamicBatcher` and
`ServingMetrics` (coalescing only ever combines same-model requests — the
compiled programs are per-model, so cross-model batching is meaningless),
while the device is shared naturally because every batcher dispatches
through the same JAX runtime and dispatches serialize there anyway.

Routing contract (served by serve/server.py):

    POST /predict            -> the DEFAULT model (first added) — the PR 3
                                single-model surface, unchanged
    POST /predict/<name>     -> that model; unknown names get 404 with the
                                served-model list in the body
    GET  /stats[/<name>]     -> per-model ServingMetrics + weight provenance
    GET  /healthz            -> aggregate: per-model provenance (epoch,
                                manifest hash, verified) so a fleet can be
                                audited for weight skew with one request

Hot weight reload (serve/reload.py) operates on `ServedModel` entries that
carry a `workdir`: the reloader polls the run dir, verifies candidates
against the PR 4 integrity manifest, and swaps verified weights into the
live engine via `PredictEngine.swap_variables` — per-model `reload_stats`
surface the outcome on /healthz.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional

from .autoscale import CircuitBreaker
from .batcher import DynamicBatcher
from .engine import PredictEngine
from .metrics import ServingMetrics


class UnknownModel(KeyError):
    """Routed model name is not served; carries the served list so the
    HTTP 404 body can say what IS available instead of being opaque."""

    def __init__(self, name: str, served: List[str]):
        super().__init__(name)
        self.name = name
        self.served = list(served)

    def __str__(self) -> str:
        return (f"unknown model {self.name!r} — served models: "
                f"{', '.join(self.served)}")


class ServedModel:
    """One model's serving unit: engine + its own batcher + its own
    metrics, plus the run dir hot reload watches (None = static weights).
    `reload_stats` is mutated by the WeightReloader and read by /healthz —
    guarded by `reload_lock` since poller and handler threads race."""

    def __init__(self, engine: PredictEngine, batcher: DynamicBatcher,
                 metrics: ServingMetrics, workdir: Optional[str] = None):
        self.engine = engine
        self.batcher = batcher
        self.metrics = metrics
        self.workdir = workdir
        # accuracy-gated promotion controller (serve/promote.py) when the
        # deployment runs candidates through shadow/canary before they go
        # live; None = the plain integrity-verified direct-swap path
        self.promoter = None
        # flywheel controller (flywheel/controller.py) when drift-triggered
        # continuous training is armed: monitors this model's live inputs
        # against the pinned calibration shard and drives
        # retrain -> re-gate -> promote episodes; None = no flywheel
        self.flywheel = None
        self.reload_lock = threading.Lock()
        self.reload_stats: Dict[str, float] = {
            "reloads": 0, "refused_corrupt": 0, "refused_incompatible": 0,
            "refused_gate": 0, "rolled_back": 0}
        # autoscale decision record, mutated by the AutoscaleController
        # under reload_lock (the control-plane lock) and read by /healthz
        self.autoscale_stats: Dict[str, float] = {
            "scale_ups": 0, "scale_downs": 0, "escalations": 0,
            "wants_scale_out": False,
            "workers": self.batcher.workers}
        # the model's documented p99 contract (max_delay + one max-bucket
        # compute time, ms) — measured lazily by the autoscaler's first
        # sample; None until then
        self.p99_bound_ms: Optional[float] = None

    @property
    def name(self) -> str:
        return self.engine.name

    @property
    def breaker(self) -> Optional[CircuitBreaker]:
        return self.batcher.breaker

    def submit(self, images, *, deadline_s: Optional[float] = None,
               precision: Optional[str] = None, trace=None):
        """Route one request into this model's batcher, tagged with the
        generation the promotion controller picks (the canary fraction
        runs on the staged candidate while one is in flight; everything
        else — and everything when no promotion is active — runs live).
        The HTTP front door and the load bench both submit through here so
        canary routing cannot be bypassed by one of them. `deadline_s`
        feeds admission control (None = the batcher's configured default);
        the breaker's fail-fast and the deadline refusal both raise from
        here, BEFORE anything is queued. `trace` is a sampled request's
        TraceContext (obs/trace.py) — the dispatcher records its queue
        wait and links it to the batch that serves it."""
        return self.submit_routed(images, deadline_s=deadline_s,
                                  precision=precision, trace=trace)[0]

    def submit_routed(self, images, *, deadline_s: Optional[float] = None,
                      precision: Optional[str] = None, trace=None):
        """`submit` plus the routing verdict: returns `(future,
        generation)` where `generation` is `"candidate"` when the promotion
        controller canary-routed this request and `"live"` otherwise — the
        per-response generation report the tier router's no-mixed-
        generation audit (serve/tier.py) pins, resolved HERE so the label
        and the routed batch can never disagree."""
        generation = self.promoter.route() if self.promoter else None
        fut = self.batcher.submit(images, generation=generation,
                                  precision=precision,
                                  deadline_s=deadline_s, trace=trace)
        return fut, (generation or "live")

    def describe(self) -> dict:
        """The /healthz per-model record: serving shape + weight
        provenance + reload outcomes + promotion/overload-control state."""
        with self.reload_lock:
            reload_stats = dict(self.reload_stats)
            autoscale_stats = dict(self.autoscale_stats)
        autoscale_stats["workers"] = self.batcher.workers
        compile_log = list(getattr(self.engine, "compile_log", ()))
        return {
            "buckets": list(self.engine.buckets),
            # startup compile evidence: how many bucket programs the boot
            # paid for vs read from the persistent XLA cache — the tier's
            # warm-boot contract (`misses == 0` on a warm shared cache) is
            # auditable per replica from one /healthz
            "compile": {
                "entries": len(compile_log),
                "cache_hits": sum(1 for e in compile_log
                                  if e.get("cache") == "hit"),
                "cache_misses": sum(1 for e in compile_log
                                    if e.get("cache") == "miss"),
                "compile_s": round(sum(e.get("compile_s", 0.0)
                                       for e in compile_log), 3),
            },
            # the int8 axis: the ACTIVE precision dispatches default to,
            # and the last calibration-gate decision (why int8 is on/off)
            "precision": getattr(self.engine, "precision", "bf16"),
            "quant": getattr(self.engine, "quant_decision", None),
            # the mesh axis beside it: axis names x sizes when the engine
            # is GSPMD-sharded (None = single chip), plus the per-chip
            # weight-byte accounting that makes the HBM win auditable
            "mesh": getattr(self.engine, "mesh_axes", None),
            "weight_bytes_per_chip": (
                self.engine.weight_bytes_per_chip()
                if hasattr(self.engine, "weight_bytes_per_chip") else None),
            "max_batch": self.batcher.max_batch,
            "queue_depth": self.batcher.queue_depth,
            "workers": self.batcher.workers,
            "default_deadline_s": self.batcher.default_deadline_s,
            "weights": self.engine.provenance,
            "hot_reload": bool(self.workdir),
            "reload": reload_stats,
            "autoscale": autoscale_stats,
            "breaker": (self.breaker.describe() if self.breaker else None),
            "promotion": (self.promoter.describe()
                          if self.promoter else None),
            "flywheel": (self.flywheel.describe()
                         if self.flywheel else None),
        }

    def snapshot(self) -> dict:
        """The /stats per-model record."""
        snap = {
            **self.metrics.snapshot(queue_depth=self.batcher.queue_depth),
            "workers": float(self.batcher.workers),
            "weights": self.engine.provenance,
            "precision": getattr(self.engine, "precision", "bf16"),
            "mesh": getattr(self.engine, "mesh_axes", None),
            "weight_bytes_per_chip": (
                self.engine.weight_bytes_per_chip()
                if hasattr(self.engine, "weight_bytes_per_chip") else None),
        }
        if self.breaker is not None:
            snap["breaker_state"] = self.breaker.describe()["state"]
        if self.promoter is not None:
            snap["promotion"] = self.promoter.describe()
        return snap


class ModelFleet:
    """Ordered name -> ServedModel map. The first model added is the
    default (`POST /predict` without a name), mirroring how the PR 3
    single-model server behaved — a one-model fleet is byte-for-byte that
    server."""

    def __init__(self):
        self._models: Dict[str, ServedModel] = {}  # insertion-ordered

    def add(self, engine: PredictEngine, *,
            workdir: Optional[str] = None,
            max_batch: Optional[int] = None,
            max_delay_ms: float = 5.0,
            max_queue_examples: int = 1024,
            workers: int = 1,
            default_deadline_s: Optional[float] = None,
            breaker_k: int = 5,
            breaker_cooldown_s: float = 5.0) -> ServedModel:
        """Register an engine under its own name with a fresh batcher and
        metrics accumulator. Per-model backpressure: one model being
        hammered sheds ITS requests (429) without starving the others'
        queues. Per-model circuit breaker likewise: one model's broken
        dispatch path fail-fasts ITS requests (503 naming the model)
        without poisoning the rest of the fleet. `workers` sizes the
        initial dispatcher pool (the autoscaler resizes it live);
        `default_deadline_s` arms admission control for requests that
        carry no deadline of their own."""
        if engine.name in self._models:
            raise ValueError(f"model {engine.name!r} already served — one "
                             f"entry per registry name")
        metrics = ServingMetrics()
        batcher = DynamicBatcher(
            engine, max_batch=max_batch, max_delay_ms=max_delay_ms,
            max_queue_examples=max_queue_examples, metrics=metrics,
            workers=workers, default_deadline_s=default_deadline_s)
        batcher.breaker = CircuitBreaker(engine.name, k=breaker_k,
                                         cooldown_s=breaker_cooldown_s)
        sm = ServedModel(engine, batcher, metrics, workdir=workdir)
        self._models[engine.name] = sm
        return sm

    # -- lookup ------------------------------------------------------------

    @property
    def default(self) -> ServedModel:
        if not self._models:
            raise RuntimeError("empty fleet: add at least one model")
        return next(iter(self._models.values()))

    def get(self, name: Optional[str] = None) -> ServedModel:
        """Resolve a routed name (None/'' = default). Raises UnknownModel
        carrying the served list — the 404 body contract."""
        if not name:
            return self.default
        try:
            return self._models[name]
        except KeyError:
            raise UnknownModel(name, self.names()) from None

    def names(self) -> List[str]:
        return list(self._models)

    def __len__(self) -> int:
        return len(self._models)

    def __iter__(self) -> Iterator[ServedModel]:
        return iter(self._models.values())

    # -- aggregates --------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return sum(sm.batcher.queue_depth for sm in self)

    @property
    def draining(self) -> bool:
        return any(sm.batcher.draining for sm in self)

    def describe(self) -> Dict[str, dict]:
        return {sm.name: sm.describe() for sm in self}

    def snapshots(self) -> Dict[str, dict]:
        return {sm.name: sm.snapshot() for sm in self}

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Drain every batcher (reject new work, finish accepted, stop the
        dispatcher threads). True once ALL dispatchers exited."""
        ok = True
        for sm in self:
            ok = sm.batcher.drain(timeout) and ok
        return ok
