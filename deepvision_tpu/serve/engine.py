"""Shape-bucketed, ahead-of-time-compiled predict engine.

The training side pays trace+compile once and then dispatches one program per
step; a naive serving loop instead pays per-request dispatch, per-shape
retrace, and batch-of-1 utilization. This engine removes the first two:
every registered model's apply fn is wrapped in a predict function that is
**AOT-compiled once per shape bucket at startup** (`jit(...).lower(...)
.compile()`, against the persistent XLA compilation cache when one is
configured — see `cli.setup_compilation_cache`), so no request ever traces
or compiles. Incoming batches are padded up to the nearest bucket
({1, 8, 32, max_batch} by default) and the padding rows are stripped from
the outputs; in inference mode (`train=False`, BatchNorm on running stats)
rows are independent, so padding provably cannot contaminate real outputs —
pinned by tests/test_serve.py's equivalence tests against direct
`model.apply`.

Dtype policy matches the training step (core/steps.py): inputs cast to the
config's compute dtype (bf16 unless the config pins f32), outputs returned
as f32.

The engine is single-device by DEFAULT — serving parallelism starts as one
engine process per chip behind a load balancer — but scales UP when handed a
mesh (`PredictEngine(..., mesh=make_mesh(...))`): params are placed once
under `NamedSharding` (big leaves sharded over the 'model' axis, the
predict-side rule in parallel/mesh.serve_param_shardings), the request batch
shards over 'data' (H rows over 'spatial' when present), and every bucket ×
precision program AOT-compiles as ONE GSPMD computation over that mesh with
**fully replicated outputs** — the gather is inside the executable, so the
batcher, fleet, promotion and HTTP layers above the engine boundary see
exactly the single-device payload. That is the lever for a model too big
(or a batch too hot) for one chip; the batch-of-1 utilization problem
remains the dynamic micro-batcher's job (serve/batcher.py).

The engine carries a **precision axis** beside the bucket axis: bf16 (the
train-matched policy above) always, plus optional int8 bucket twins armed
by the calibrated quantization gate (serve/quantize.arm_int8 — per-channel
weight scales, pinned per-tensor activation scales, f32 heads preserved).
`precision` is the model's active default; every compiled precision stays
per-request addressable (`predict(..., precision=)`), and both weight
generations exist at both precisions so promotion/hot reload never compare
across precisions.

The engine can host TWO weight generations at once: the live one every
ordinary dispatch uses, and a staged candidate (`stage_candidate`) the
accuracy-gated promotion pipeline (serve/promote.py) shadow-evaluates and
canary-routes before flipping it live (`promote_candidate`) or retreating
(`drop_candidate`). Both generations run through the same AOT bucket
executables — equal weight signatures mean zero recompiles — and every
dispatch resolves exactly one generation's variables on entry, so no batch
ever mixes weights.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# the ONE definition of on-device input normalization, shared with the
# train/eval steps so serving can never drift from the training dtype policy
from ..core.steps import _normalize_input
# predict-side placement contract (mesh serving): param/input/output
# shardings and the per-chip byte accounting /healthz reports
from ..parallel.mesh import (per_chip_bytes, serve_param_shardings,
                             serve_shardings)

# the engine's precision axis: "bf16" is the train-matched compute policy
# (f32 for configs that pin f32), "int8" the calibrated post-training
# quantization twin compiled beside it (serve/quantize.py). Selected
# per-model by the quant gate (`--serve-precision int8`) or per-request.
PRECISIONS = ("bf16", "int8")


def load_checkpoint_weights(name: str, workdir: str, *,
                            checkpoint=None, image_size: Optional[int] = None,
                            verify: bool = True, verbose: bool = True):
    """Restore a registered config's SERVING weights from a training
    workdir: the checkpoint is restored through the config's own trainer
    family, EMA weights win when present (exactly the weights validation
    scored, `Trainer.eval_state`), and `verify=True` restores in STRICT
    integrity mode — a checkpoint whose manifest does not verify raises
    CheckpointCorruptionError instead of returning silently corrupt
    weights.

    Returns `(apply_fn, variables, provenance, cfg)` where `variables` is
    the host-side `{params[, batch_stats]}` dict an engine dispatches with
    and `provenance` is the `{weights, checkpoint_epoch, verified,
    manifest_sha256, resharded}` record /healthz reports. Shared by
    `PredictEngine.from_config` (startup) and `reload.WeightReloader`
    (hot swap) so the two paths can never verify differently.

    Elastic wire-through (core/reshard.py): the restore runs through the
    trainer's mesh-aware CheckpointManager, so a checkpoint saved on a
    multi-chip pod loads (and hot-reloads) on this host's device count
    without manual surgery — the manifest's verified shapes/hashes are the
    re-slicing source of truth, and `resharded: true` lands in the
    provenance so a fleet audit can see which replicas crossed a mesh."""
    from ..configs import get_config, trainer_class_for_config
    cfg = get_config(name)
    if cfg.family == "gan":
        raise ValueError(
            f"config {name!r} is adversarial — serve a generator via "
            f"tools/export.py instead (no single logits apply fn)")
    image_size = image_size or cfg.data.image_size
    sample_shape = (image_size, image_size, cfg.data.channels)
    trainer = trainer_class_for_config(name)(cfg, workdir=workdir)
    try:
        trainer.init_state(sample_shape)
        got = trainer.resume(
            None if checkpoint in (None, "latest") else int(checkpoint),
            verify="strict" if verify else "off")
        if got is None and verbose:
            print(f"[serve:{cfg.name}] WARNING: nothing restorable "
                  f"in {workdir!r} — serving RANDOM weights",
                  flush=True)
        info = trainer.ckpt.last_restore_info or {}
        provenance = {
            "weights": ("checkpoint" if got is not None
                        else "random-init"),
            "checkpoint_epoch": got,
            "verified": bool(info.get("verified", False)),
            "manifest_sha256": info.get("manifest_sha256"),
            "resharded": bool(info.get("resharded", False)),
        }
        if (got is not None and not provenance["verified"]
                and verbose):
            print(f"[serve:{cfg.name}] WARNING: serving UNVERIFIED "
                  f"weights (epoch {got}: "
                  f"{'legacy checkpoint without a manifest' if info.get('legacy') else 'verification off'})",
                  flush=True)
        st = trainer.eval_state()
        apply_fn = st.apply_fn
        params = jax.device_get(st.params)
        batch_stats = jax.device_get(st.batch_stats)
    finally:
        trainer.close()
    variables = {"params": params}
    if jax.tree_util.tree_leaves(batch_stats):
        variables["batch_stats"] = batch_stats
    return apply_fn, variables, provenance, cfg


def weight_signature(variables, shardings=None):
    """(treedef, [(shape, dtype[, spec]), ...]) of a variables pytree — the
    compiled-executable compatibility key hot reload checks before a swap:
    equal signatures mean the AOT bucket programs run the new weights
    as-is (zero recompiles); anything else needs a new engine. On a mesh
    engine the per-leaf PLACEMENT is part of that key: `shardings` (the
    per-leaf NamedSharding tree the weights are — or would be — placed
    under) extends each entry with its partition spec, so a swap is refused
    unless the candidate lands under shardings equal to the compiled
    ones, not just equal shapes."""
    leaves, treedef = jax.tree_util.tree_flatten(variables)
    sig = [(tuple(np.shape(leaf)),
            str(getattr(leaf, "dtype", np.asarray(leaf).dtype)))
           for leaf in leaves]
    if shardings is not None:
        specs = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda s: hasattr(s, "spec"))
        sig = [(*entry, str(s.spec)) for entry, s in zip(sig, specs)]
    return treedef, sig


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (buckets ascending). Raises past the largest
    bucket — predict() chunks oversize batches before calling this, and the
    batcher never coalesces past max_batch."""
    if n < 1:
        raise ValueError(f"need at least one example, got {n}")
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds the largest bucket {buckets[-1]}")


def tree_slice(outputs, lo: int, hi: int):
    """Per-leaf `[lo:hi]` over an output pytree (detection/pose models
    return tuples of per-scale arrays; classification a single array)."""
    return jax.tree_util.tree_map(lambda a: a[lo:hi], outputs)


def tree_concat(chunks: Sequence[Any]):
    """Concatenate a list of same-structure output pytrees along batch."""
    return jax.tree_util.tree_map(lambda *xs: np.concatenate(xs), *chunks)


class PredictEngine:
    """Bucketed AOT predict cache over `apply_fn(variables, x, train=False)`.

    `predict(images)` accepts a host array of shape `(n, *example_shape)`
    (or one bare example), pads to the nearest bucket, runs ONE compiled
    dispatch per <=max_batch chunk, and returns the host output pytree with
    the padding rows stripped. Thread-safe: dispatches serialize on the
    device, and the compiled executables are stateless.
    """

    def __init__(self, apply_fn: Callable, variables, *,
                 example_shape: Sequence[int],
                 buckets: Sequence[int] = (1, 8, 32),
                 max_batch: Optional[int] = None,
                 compute_dtype=jnp.bfloat16,
                 input_norm: Optional[Tuple] = None,
                 take_first_output: bool = False,
                 output_transform: Optional[Callable] = None,
                 name: str = "model", verbose: bool = True,
                 provenance: Optional[dict] = None,
                 mesh=None):
        bs = sorted({int(b) for b in buckets})
        if not bs or bs[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        self.mesh = mesh
        self.mesh_axes = dict(mesh.shape) if mesh is not None else None
        if mesh is not None:
            # the 'data' axis shards the request batch, so every bucket
            # (and max_batch) must be a data-axis multiple: round them UP —
            # the padding machinery already pads n -> bucket, so a bucket
            # of 1 on a data=2 mesh simply becomes 2 with one padding row
            data = int(self.mesh_axes.get("data", 1))
            bs = sorted({-(-b // data) * data for b in bs})
            if max_batch:
                max_batch = -(-int(max_batch) // data) * data
        max_batch = int(max_batch) if max_batch else bs[-1]
        if max_batch < bs[-1]:
            raise ValueError(f"max_batch={max_batch} below the largest "
                             f"bucket {bs[-1]}")
        if max_batch not in bs:
            bs.append(max_batch)  # the {1, 8, 32, max_batch} policy
        self.buckets: Tuple[int, ...] = tuple(bs)
        self.max_batch = max_batch
        self.example_shape = tuple(example_shape)
        self.name = name
        # weight provenance, reported on /healthz and /stats so a fleet of
        # replicas can be audited for skew (same epoch? same manifest hash?
        # verified?) — filled by from_config when restoring a checkpoint
        self.provenance = dict(provenance or {
            "weights": "random-init", "checkpoint_epoch": None,
            "verified": False, "manifest_sha256": None, "resharded": False})
        self.input_dtype = np.dtype(np.uint8 if input_norm is not None
                                    else np.float32)
        # params are committed ONCE — compiled calls reuse the buffers
        # instead of re-staging them per request. Single device by default;
        # on a mesh each leaf lands under its NamedSharding from the
        # predict-side placement contract (parallel/mesh.serve_shardings):
        # big leaves sharded over 'model', the batch over 'data' (+H rows
        # over 'spatial' when it divides), outputs fully REPLICATED so the
        # layers above the engine boundary see single-device payloads
        if mesh is not None:
            (self._param_shardings, self._in_sharding,
             self._out_sharding) = serve_shardings(
                 mesh, variables, self.example_shape)
            self._placement = self._param_shardings
            self._device = None
        else:
            self._param_shardings = None
            self._in_sharding = self._out_sharding = None
            self._device = jax.devices()[0]
            self._placement = self._device
        self._variables = jax.device_put(variables, self._placement)
        self._stamp_provenance()
        # second weight generation (the promotion pipeline's CANDIDATE,
        # serve/promote.py): staged on the same device, served only to
        # dispatches that ask for generation="candidate" — shadow eval and
        # canary traffic — through the SAME compiled bucket programs (the
        # executables take variables as an argument, so hosting two
        # signature-equal generations costs zero recompiles). None = only
        # the live generation exists.
        self._candidate = None
        self.candidate_provenance: Optional[dict] = None
        self._candidate_delay_s = 0.0   # fault injection: canary latency spike
        # -- int8 precision axis (serve/quantize.py) -----------------------
        # armed by enable_int8: a Quantizer (pinned activation scales +
        # per-generation weight quantization), the quantized weight trees
        # for both generations, and the int8 bucket executables compiled
        # BESIDE the bf16 ones. `precision` is the model's ACTIVE default
        # — flipped to "int8" only after the accuracy gate passes; either
        # precision stays per-request addressable while both are compiled.
        self.precision: str = "bf16"
        self.quant_decision: Optional[dict] = None   # last gate verdict
        self._quantizer = None
        self._qvariables = None
        self._qcandidate = None
        self._qplacement = None   # mesh: the quantized tree's own shardings
        self._compiled_int8: dict = {}

        def predict(variables, images):
            x = _normalize_input(images, input_norm, compute_dtype)
            out = apply_fn(variables, x, train=False)
            if take_first_output and isinstance(out, (tuple, list)):
                out = out[0]  # inception-style aux heads: primary logits
            if output_transform is not None:
                # family-level payload shaping compiled INTO the bucket
                # programs (segmentation: f32 logits -> int32 class-id
                # masks) — the argmax ships in the AOT executable, so the
                # wire payload is C-fold smaller than the logits
                out = output_transform(out)
            # float leaves serve as f32 (the engine contract jaxvet's
            # DTYPE family checks); integer payloads (class-id masks)
            # keep their dtype
            return jax.tree_util.tree_map(
                lambda y: y.astype(jnp.float32)
                if jnp.issubdtype(y.dtype, jnp.floating) else y, out)

        self._predict_fn = predict
        if mesh is not None:
            # ONE GSPMD computation per bucket over the mesh: in_shardings
            # pin the placement contract (sharded params, 'data'-sharded
            # batch), out_shardings=replicated compiles the gather INTO the
            # executable — still AOT (.lower().compile() below), still zero
            # per-request traces
            self._jitted = jax.jit(
                predict,
                in_shardings=(self._param_shardings, self._in_sharding),
                out_shardings=self._out_sharding)
        else:
            self._jitted = jax.jit(predict)
        self._compiled = {}
        self.compile_log: list = []
        self._compile_all(verbose)

    # -- mesh placement ----------------------------------------------------

    def _stamp_provenance(self) -> None:
        # the serve-side placement is ENGINE state, not checkpoint state:
        # re-stamped after every provenance-carrying swap so /healthz always
        # shows the mesh the current weights are placed on (None = one chip)
        self.provenance["mesh"] = self.mesh_axes

    def _sig(self, variables):
        """Signature of `variables` as this engine would compile/place it —
        shape/dtype per leaf, plus the per-leaf partition spec on a mesh
        engine (the placement rule is a pure function of leaf shapes, so
        candidates are keyed by the shardings they WOULD land under)."""
        if self.mesh is None:
            return weight_signature(variables)
        return weight_signature(
            variables, serve_param_shardings(self.mesh, variables))

    def _place_input(self, x: np.ndarray):
        # host batch -> the compiled program's input placement ('data'-
        # sharded on a mesh); single-device executables take host arrays
        # directly
        if self._in_sharding is None:
            return x
        return jax.device_put(x, self._in_sharding)

    def weight_bytes_per_chip(self) -> dict:
        """Resident weight bytes on the single busiest device, per compiled
        precision (`int8` is None until the quant gate arms it) — the
        HBM-per-chip accounting /healthz, /stats and `bench_serve.py
        --mesh` report. On a model-parallel mesh this is the whole point:
        the figure drops by ~the model-axis size vs a single-chip engine."""
        return {"bf16": per_chip_bytes(self._variables),
                "int8": (per_chip_bytes(self._qvariables)
                         if self._qvariables is not None else None)}

    # -- construction ------------------------------------------------------

    @classmethod
    def from_config(cls, name: str, *, workdir: Optional[str] = None,
                    checkpoint=None, image_size: Optional[int] = None,
                    buckets: Sequence[int] = (1, 8, 32),
                    max_batch: Optional[int] = None,
                    verbose: bool = True,
                    verify: bool = True,
                    mesh=None) -> "PredictEngine":
        """Build an engine for a registered config. With a `workdir`, the
        latest (or given-epoch) checkpoint is restored through the config's
        own trainer family — EMA weights win when present, exactly the
        weights validation scored (`Trainer.eval_state`); without one, the
        params are a fresh init (smoke/bench use).

        `verify=True` (default) restores in STRICT integrity mode: a
        checkpoint whose manifest does not verify raises
        CheckpointCorruptionError instead of serving silently corrupt
        weights (`--no-verify` on the serve CLI disables; a legacy workdir
        with no manifests serves with a warning and `verified: false`
        provenance). The resulting provenance — checkpoint epoch, manifest
        hash, verified flag — lands on `engine.provenance` and the
        server's /healthz and /stats.

        `mesh` (parallel/mesh.make_mesh) makes this a mesh-sharded engine:
        the restore path is unchanged — the trainer's mesh-aware
        CheckpointManager already lands ANY saved topology on this host
        (`resharded` provenance), and the engine then places the host tree
        under the serve mesh's shardings — so a 1-chip checkpoint serves
        model-parallel and a pod checkpoint serves on one chip."""
        from ..configs import get_config
        cfg = get_config(name)
        if cfg.family == "gan":
            raise ValueError(
                f"config {name!r} is adversarial — serve a generator via "
                f"tools/export.py instead (no single logits apply fn)")
        image_size = image_size or cfg.data.image_size
        sample_shape = (image_size, image_size, cfg.data.channels)
        compute_dtype = jnp.dtype(cfg.dtype) if cfg.dtype else jnp.bfloat16
        provenance = None
        if workdir:
            apply_fn, variables, provenance, cfg = load_checkpoint_weights(
                name, workdir, checkpoint=checkpoint, image_size=image_size,
                verify=verify, verbose=verbose)
        else:
            from ..core.train_state import init_model
            from ..core.trainer import build_model_from_config
            model, cfg = build_model_from_config(cfg)
            params, batch_stats = init_model(
                model, jax.random.PRNGKey(cfg.seed),
                jnp.zeros((2, *sample_shape), jnp.float32))
            apply_fn = model.apply
            variables = {"params": params}
            if jax.tree_util.tree_leaves(batch_stats):
                variables["batch_stats"] = batch_stats
        input_norm = ((cfg.data.mean, cfg.data.std)
                      if cfg.data.normalize_on_device else None)
        output_transform = None
        if cfg.family == "segmentation":
            # dense prediction serves CLASS-ID MASKS, not logits: argmax
            # inside the compiled program (int32 (n, H, W) payload) — the
            # same transform core/segment.make_segmentation_predict_step
            # applies, mirrored by the jaxvet SERVE probe
            def output_transform(out):
                return jnp.argmax(out, axis=-1).astype(jnp.int32)
        return cls(apply_fn, variables, example_shape=sample_shape,
                   buckets=buckets, max_batch=max_batch,
                   compute_dtype=compute_dtype, input_norm=input_norm,
                   take_first_output=cfg.family == "classification",
                   output_transform=output_transform,
                   name=cfg.name, verbose=verbose, provenance=provenance,
                   mesh=mesh)

    # -- compilation -------------------------------------------------------

    def _compile_all(self, verbose: bool) -> None:
        """AOT-compile every bucket up front — startup pays all compiles
        (or persistent-cache reads), requests pay none. Per-bucket
        hit/miss is logged so a cold cache is visible, not mysterious."""
        from ..cli import compilation_cache_stats, install_cache_stats_hooks
        install_cache_stats_hooks()
        for b in self.buckets:
            before = compilation_cache_stats()
            t0 = time.perf_counter()
            spec = jax.ShapeDtypeStruct((b, *self.example_shape),
                                        self.input_dtype)
            self._compiled[b] = self._jitted.lower(
                self._variables, spec).compile()
            dt = time.perf_counter() - t0
            after = compilation_cache_stats()
            if after["hits"] > before["hits"]:
                cache = "hit"
            elif after["misses"] > before["misses"]:
                cache = "miss"
            else:
                cache = "off"
            self.compile_log.append(
                {"bucket": b, "compile_s": round(dt, 3), "cache": cache,
                 "precision": "bf16"})
            if verbose:
                print(f"[serve:{self.name}] bucket {b}: compiled in "
                      f"{dt:.2f}s (persistent-cache {cache})", flush=True)

    def warmup(self) -> None:
        """One blocking dispatch per bucket: absorbs first-call transfer and
        runtime setup so the first real request doesn't pay it."""
        x = np.zeros((self.max_batch, *self.example_shape), self.input_dtype)
        for b in self.buckets:
            xb = self._place_input(x[:b])
            jax.block_until_ready(self._compiled[b](self._variables, xb))
            if b in self._compiled_int8:
                jax.block_until_ready(
                    self._compiled_int8[b](self._qvariables, xb))

    # -- int8 precision axis (serve/quantize.py) ---------------------------

    @property
    def int8_enabled(self) -> bool:
        return bool(self._compiled_int8)

    def enable_int8(self, quantizer, verbose: bool = True) -> None:
        """Compile the int8 bucket twins beside the bf16 cache — the
        ONE-TIME arm cost (serve/quantize.arm_int8 drives this and gates
        the result before flipping `precision`). Per bucket the quantizer
        re-traces the predict at that batch size and bakes its pinned
        activation scales; the quantized weight tree is staged once. The
        active precision is NOT changed here — that is the gate's call."""
        from ..cli import compilation_cache_stats, install_cache_stats_hooks
        install_cache_stats_hooks()
        self._quantizer = quantizer
        # the quantized tree has its OWN structure (int8 payloads + f32
        # scales), so on a mesh it gets its own shardings from the same
        # predict-side placement rule — precision and mesh COMPOSE: sharded
        # int8 buckets cut HBM-per-chip twice over. The scale math itself
        # is placement-independent (run on a host copy on a mesh), so both
        # engine kinds quantize bit-identically.
        src = (jax.device_get(self._variables) if self.mesh is not None
               else self._variables)
        qvars = quantizer.quantize(src)
        self._qplacement = (serve_param_shardings(self.mesh, qvars)
                            if self.mesh is not None else self._device)
        self._qvariables = jax.device_put(qvars, self._qplacement)
        jax.block_until_ready(self._qvariables)
        for b in self.buckets:
            before = compilation_cache_stats()
            t0 = time.perf_counter()
            self._compile_int8_bucket(quantizer, b)
            dt = time.perf_counter() - t0
            after = compilation_cache_stats()
            if after["hits"] > before["hits"]:
                cache = "hit"
            elif after["misses"] > before["misses"]:
                cache = "miss"
            else:
                cache = "off"
            self.compile_log.append(
                {"bucket": b, "compile_s": round(dt, 3), "cache": cache,
                 "precision": "int8"})
            if verbose:
                print(f"[serve:{self.name}] int8 bucket {b}: compiled in "
                      f"{dt:.2f}s (persistent-cache {cache})", flush=True)

    def _compile_int8_bucket(self, quantizer, b: int) -> None:
        # one AOT compile per (bucket, quantized twin): each bucket's
        # quantized predict is a DISTINCT function (its jaxpr is baked at
        # that batch size), so this is the factory site, not a retrace
        spec = jax.ShapeDtypeStruct((b, *self.example_shape),
                                    self.input_dtype)
        qfn = quantizer.quantized_fn(self._variables, spec)
        if self.mesh is not None:
            jitted = jax.jit(qfn,
                             in_shardings=(self._qplacement,
                                           self._in_sharding),
                             out_shardings=self._out_sharding)
        else:
            jitted = jax.jit(qfn)
        self._compiled_int8[b] = jitted.lower(
            self._qvariables, spec).compile()

    def disable_int8(self) -> None:
        """Retreat to bf16-only serving (the gate's refusal path): the
        quantized tree and int8 executables are dropped, the active
        precision returns to bf16. The gate's decision record
        (`quant_decision`) is kept — /healthz shows WHY int8 is off."""
        self.precision = "bf16"
        self._quantizer = None
        self._qvariables = None
        self._qcandidate = None
        self._qplacement = None
        self._compiled_int8 = {}

    def set_precision(self, precision: str) -> None:
        """Flip the model's ACTIVE precision (dispatches that don't ask for
        one explicitly). int8 requires armed+compiled int8 buckets."""
        if precision not in PRECISIONS:
            raise ValueError(f"unknown precision {precision!r} "
                             f"(expected one of {PRECISIONS})")
        if precision == "int8" and not self.int8_enabled:
            raise ValueError(
                f"int8 serving is not armed for {self.name!r} — run the "
                f"calibration gate first (serve/quantize.arm_int8, or "
                f"--serve-precision int8 on the serve CLI)")
        self.precision = precision

    # -- hot weight reload -------------------------------------------------

    def swap_variables(self, variables, provenance: Optional[dict] = None
                       ) -> None:
        """Atomically swap the live weights — the hot-reload primitive
        (serve/reload.py). The new variables must match the current tree
        structure and per-leaf shapes/dtypes EXACTLY: the AOT bucket
        executables were compiled against those avals, so an equal
        signature means they run the new weights with zero recompiles,
        and anything else is refused (a changed architecture needs a new
        engine, not a swap). Staging (device_put + block) happens BEFORE
        the swap, off the request path; the swap itself is one reference
        assignment, so in-flight dispatches — which captured the old
        reference on entry to `_dispatch` — complete against the old
        weights and every later dispatch sees the new ones."""
        new_sig = self._sig(variables)
        old_sig = weight_signature(self._variables, self._param_shardings)
        if new_sig != old_sig:
            raise ValueError(
                f"refusing hot swap for {self.name!r}: new weights do not "
                f"match the compiled signature (tree structure, leaf "
                f"shapes/dtypes or shardings differ) — the AOT bucket "
                f"programs would need a recompile; build a fresh engine "
                f"instead")
        # candidate weights RE-PLACE under the exact shardings the programs
        # were compiled against (on a mesh: the same NamedShardings the
        # signature just keyed on) — so hot reload lands a checkpoint from
        # ANY saved topology on this serve mesh with zero recompiles
        staged = jax.device_put(variables, self._placement)
        qstaged = None
        if self._quantizer is not None:
            # int8 stays a first-class citizen through hot reload: the new
            # generation re-quantizes under the PINNED activation scales
            # (weight scales are data-free) — same shapes/dtypes, so the
            # compiled int8 buckets run it as-is, zero recompiles
            qstaged = jax.device_put(self._quantizer.quantize(variables),
                                     self._qplacement)
            jax.block_until_ready(qstaged)
        jax.block_until_ready(staged)   # fully resident before going live
        self._variables = staged
        if qstaged is not None:
            self._qvariables = qstaged
        if provenance is not None:
            self.provenance = dict(provenance)
            self._stamp_provenance()

    # -- candidate generation (staged promotion, serve/promote.py) ---------

    @property
    def has_candidate(self) -> bool:
        return self._candidate is not None

    def stage_candidate(self, variables, provenance: Optional[dict] = None,
                        *, inject_delay_s: float = 0.0) -> None:
        """Host a second weight generation beside the live one. Same
        signature contract as `swap_variables` (equal tree/shapes/dtypes,
        else ValueError — the compiled programs must run both generations
        as-is); staging is device_put + block, off the request path.
        Dispatches keep defaulting to the live generation: only callers
        that ask for `generation="candidate"` (the promotion controller's
        shadow eval and canary-routed batches) see these weights.
        `inject_delay_s` is the deterministic canary latency-spike fault
        (DEEPVISION_FAULT_PROMOTE_REGRESS=<epoch>:latency) — every
        candidate-generation dispatch sleeps that long."""
        new_sig = self._sig(variables)
        old_sig = weight_signature(self._variables, self._param_shardings)
        if new_sig != old_sig:
            raise ValueError(
                f"refusing to stage candidate for {self.name!r}: weights do "
                f"not match the compiled signature (tree structure, leaf "
                f"shapes/dtypes or shardings differ) — the AOT bucket "
                f"programs would need a recompile; build a fresh engine "
                f"instead")
        staged = jax.device_put(variables, self._placement)
        jax.block_until_ready(staged)
        if self._quantizer is not None:
            # both generations exist at BOTH precisions while staged: the
            # canary fraction must run on the candidate at the model's
            # active precision, or the comparison would measure precision,
            # not weights
            qcand = jax.device_put(self._quantizer.quantize(variables),
                                   self._qplacement)
            jax.block_until_ready(qcand)
            self._qcandidate = qcand
        self._candidate = staged
        self.candidate_provenance = dict(provenance) if provenance else None
        self._candidate_delay_s = float(inject_delay_s)

    def promote_candidate(self) -> dict:
        """Flip the candidate generation live — one reference assignment,
        exactly like `swap_variables`: in-flight batches (which resolved
        their generation's variables at dispatch) finish on the weights
        they started with; every later dispatch serves the new epoch.
        Returns the now-live provenance."""
        if self._candidate is None:
            raise RuntimeError(f"{self.name!r} has no staged candidate to "
                               f"promote")
        self._variables = self._candidate
        if self._qcandidate is not None:
            self._qvariables = self._qcandidate   # int8 flips in lockstep
        if self.candidate_provenance is not None:
            self.provenance = dict(self.candidate_provenance)
            self._stamp_provenance()
        self.drop_candidate()
        return self.provenance

    def drop_candidate(self) -> None:
        """Retreat to the incumbent: unstage the candidate. Later
        `generation="candidate"` dispatches resolve to the live weights (a
        rolled-back canary request still gets a single-generation answer —
        the incumbent's)."""
        self._candidate = None
        self._qcandidate = None
        self.candidate_provenance = None
        self._candidate_delay_s = 0.0

    def _resolve_precision(self, precision: Optional[str]) -> str:
        if precision is None:
            return self.precision
        if precision not in PRECISIONS:
            raise ValueError(f"unknown precision {precision!r} "
                             f"(expected one of {PRECISIONS})")
        if precision == "int8" and not self.int8_enabled:
            raise ValueError(
                f"int8 serving is not armed for {self.name!r} — the "
                f"calibration gate has not passed (see /healthz quant)")
        return precision

    def _resolve_generation(self, generation: Optional[str],
                            precision: str = "bf16"):
        """One-shot read of a generation's (variables, injected_delay_s)
        at the requested precision: the caller holds the returned reference
        for the whole dispatch, so a concurrent promote/drop never mixes
        weights inside a batch."""
        if generation in (None, "live"):
            return (self._qvariables if precision == "int8"
                    else self._variables), 0.0
        if generation != "candidate":
            raise ValueError(f"unknown weight generation {generation!r} "
                             f"(expected 'live' or 'candidate')")
        cand = (self._qcandidate if precision == "int8"
                else self._candidate)   # racing drop_candidate: read once
        if cand is None:
            return (self._qvariables if precision == "int8"
                    else self._variables), 0.0
        return cand, self._candidate_delay_s

    # -- prediction --------------------------------------------------------

    def _coerce(self, images) -> np.ndarray:
        x = np.asarray(images, self.input_dtype)
        if x.shape == self.example_shape:
            x = x[None]
        if x.ndim != len(self.example_shape) + 1 \
                or x.shape[1:] != self.example_shape:
            raise ValueError(
                f"expected (n, {', '.join(map(str, self.example_shape))}) "
                f"(or one bare example), got {x.shape}")
        return x

    def predict(self, images, generation: Optional[str] = None,
                precision: Optional[str] = None):
        """Host-in host-out bucketed prediction (pads, dispatches, strips).
        Oversize batches run as max_batch chunks plus one tail bucket.
        `generation` selects the weight set ('live'/None, or 'candidate'
        while a promotion has one staged); `precision` the compiled ladder
        ('bf16'/'int8'; None = the model's active precision) — each
        dispatch runs against exactly one generation's variables through
        exactly one precision's executables."""
        x = self._coerce(images)
        n = x.shape[0]
        if n <= self.max_batch:
            return self._dispatch(x, generation, precision)
        return tree_concat([self._dispatch(x[i:i + self.max_batch],
                                           generation, precision)
                            for i in range(0, n, self.max_batch)])

    def _dispatch(self, x: np.ndarray, generation: Optional[str] = None,
                  precision: Optional[str] = None):
        precision = self._resolve_precision(precision)
        variables, delay_s = self._resolve_generation(generation, precision)
        if delay_s > 0:
            time.sleep(delay_s)   # injected canary latency spike (faults)
        n = x.shape[0]
        b = pick_bucket(n, self.buckets)
        if b != n:
            x = np.pad(x, [(0, b - n)] + [(0, 0)] * (x.ndim - 1))
        compiled = (self._compiled_int8 if precision == "int8"
                    else self._compiled)
        out = compiled[b](variables, self._place_input(x))
        return tree_slice(jax.device_get(out), 0, n)

    def reference(self, images, generation: Optional[str] = None):
        """Eager, un-bucketed predict at the exact batch size — the direct
        `model.apply` oracle the padding-equivalence tests (and preflight's
        serve check) compare the bucketed path against. Always the bf16
        (train-matched) path: this IS the accuracy reference the int8 gate
        scores against."""
        x = self._coerce(images)
        variables, _ = self._resolve_generation(generation, "bf16")
        if self.mesh is not None:
            # eager apply against mesh-sharded params: replicate the batch
            # so computation-follows-sharding has an unambiguous layout
            x = jax.device_put(x, self._out_sharding)
        return jax.device_get(self._predict_fn(variables, jnp.asarray(x)))

    # -- measurement -------------------------------------------------------

    def measure_batch_ms(self, bucket: Optional[int] = None,
                         iters: int = 5,
                         precision: Optional[str] = None) -> float:
        """Steady-state wall time of one compiled dispatch of `bucket`
        (default max_batch) at `precision` (default: the active one), in
        ms — the "one batch compute time" term of the serving latency
        contract (docs/SERVING.md)."""
        precision = self._resolve_precision(precision)
        b = pick_bucket(bucket or self.max_batch, self.buckets)
        x = self._place_input(
            np.zeros((b, *self.example_shape), self.input_dtype))
        if precision == "int8":
            c, variables = self._compiled_int8[b], self._qvariables
        else:
            c, variables = self._compiled[b], self._variables
        jax.block_until_ready(c(variables, x))  # warm
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = c(variables, x)
        jax.block_until_ready(out)  # same device: prior dispatches serialized
        return (time.perf_counter() - t0) / iters * 1000.0
