"""Serving metrics: latency quantiles, batching efficiency, padding waste.

One thread-safe accumulator the batcher feeds per dispatched batch; the
server flushes snapshots onto the SAME metrics stream the trainer uses
(core/metrics.MetricsLogger → console echo + `serve.jsonl` + TensorBoard
when a workdir is given), so serving runs leave the same forensics trail
training runs do.

The numbers that matter (docs/SERVING.md):
- `p50_ms` / `p99_ms`: request latency submit→result over a bounded window.
  The healthy contract is p99 <= max_delay_ms + one max-bucket compute time;
  p99 far above it means overload (queueing), far below p50 ~= max_delay
  means the deadline is doing nothing (traffic always fills batches).
- `p50_queue_ms` / `p99_queue_ms` / `mean_queue_wait_ms` vs
  `mean_dispatch_ms`: latency SPLIT into its two components — time spent
  waiting for a batch slot (coalescing + backlog) vs time inside the
  device dispatch. The p99 bound above conflates them; when it is blown,
  this split says whether the cure is workers/shedding (queue-dominated)
  or a smaller bucket/model (dispatch-dominated). Both components also
  feed fixed-bucket lifetime histograms (`histograms()`) rendered on
  `GET /metrics` as Prometheus histograms (docs/OBSERVABILITY.md).
- `padding_waste`: fraction of dispatched device rows that were padding —
  the price of shape bucketing. High waste at low traffic is fine (the
  rows are free when the chip is idle); high waste at HIGH traffic means
  the bucket ladder is too coarse for the arriving batch sizes.
- `mean_batch_fill` / `batches_per_sec` / `images_per_sec`: how well the
  coalescing window converts request concurrency into device batch size.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Optional, Sequence

import numpy as np

# fixed histogram buckets (seconds): spans 1ms (the coalescing floor) to
# 10s (the serve CLI's default deadline); values past the last edge land in
# the implicit +Inf bucket. Fixed — not adaptive — so scrapes from every
# replica aggregate, the whole point of exposition histograms.
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class _Histogram:
    """Lifetime fixed-bucket histogram (Prometheus semantics): per-bucket
    counts plus sum/count, NEVER reset — rendered cumulatively with a +Inf
    bucket by `render()`. Callers hold the owning ServingMetrics lock."""

    __slots__ = ("edges", "counts", "sum", "count")

    def __init__(self, edges: Sequence[float] = LATENCY_BUCKETS_S):
        self.edges = tuple(edges)
        self.counts = [0] * (len(self.edges) + 1)   # last = > max edge
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.edges, v)] += 1
        self.sum += v
        self.count += 1

    def render(self) -> dict:
        """{"buckets": [(le, cumulative_count), ..., (inf, count)],
        "sum": float, "count": int} — the exposition shape."""
        cum, buckets = 0, []
        for le, n in zip(self.edges, self.counts):
            cum += n
            buckets.append((le, cum))
        buckets.append((float("inf"), self.count))
        return {"buckets": buckets, "sum": self.sum, "count": self.count}


class ServingMetrics:
    """Cumulative counters since construction (or the last reset) plus a
    bounded latency window. All methods are thread-safe.

    Two horizons: the interval counters (zeroed by `snapshot(reset=True)`,
    the server's periodic flush) and the LIFETIME totals (`totals()`, never
    reset) — the autoscale control loop samples deltas of the totals, so
    its shed/overload evidence cannot be erased out from under it by a
    concurrent metrics flush."""

    def __init__(self, window: int = 4096):
        self._lock = threading.Lock()
        self._window = window
        # lifetime totals — survive every reset (autoscaler's sample source)
        self._totals = {"requests": 0, "examples": 0, "shed": 0,
                        "admission_rejected": 0, "deadline_expired": 0,
                        "breaker_rejected": 0, "dispatch_errors": 0,
                        "observer_errors": 0}
        # lifetime fixed-bucket histograms (never reset — /metrics renders
        # them as Prometheus histograms, which must be monotone per scrape),
        # keyed {name: {precision: _Histogram}} — the precision axis the
        # int8 serving path added (docs/SERVING.md "Quantized serving"):
        # int8 and bf16 batches land in separate labeled series, so a
        # precision flip is visible in the scrape, not averaged away
        self._hist = {"request_latency_seconds": {},
                      "queue_wait_seconds": {},
                      "dispatch_seconds": {}}
        self._reset_locked(time.monotonic())

    def _reset_locked(self, now: float) -> None:
        self._t0 = now
        self._lat: deque = deque(maxlen=self._window)
        self._qwait: deque = deque(maxlen=self._window)
        self._queue_wait_s = 0.0
        self._requests = 0
        self._examples = 0
        self._batches = 0
        self._rows = 0          # device rows dispatched, padding included
        self._dispatch_s = 0.0
        self._shed = 0          # requests rejected at the door (Overloaded)
        # overload-control interval counters (docs/SERVING.md "Overload
        # control"): refusals at the door by kind, plus failure evidence
        self._admission_rejected = 0   # DeadlineUnmeetable (fast 503)
        self._deadline_expired = 0     # accepted, answered 504 past deadline
        self._breaker_rejected = 0     # CircuitOpen fail-fast 503
        self._dispatch_errors = 0      # engine dispatches that raised
        self._observer_errors = 0      # per-batch observer tap exceptions

    def _hist_for(self, name: str, precision: str) -> _Histogram:
        by_precision = self._hist[name]
        h = by_precision.get(precision)
        if h is None:
            h = by_precision[precision] = _Histogram()
        return h

    def observe_batch(self, *, n_real: int, bucket: int, dispatch_s: float,
                      request_latencies_s: Sequence[float],
                      queue_waits_s: Optional[Sequence[float]] = None,
                      precision: str = "bf16") -> None:
        """One dispatched batch. `queue_waits_s` (per request, submit
        acceptance -> dispatch start) separates the queueing component of
        latency from `dispatch_s` (the device's share) — the two used to be
        conflated inside the submit->result latencies, leaving the p99
        bound unable to say WHERE a blown deadline went. `precision` labels
        the histogram series the batch lands in (the engine precision its
        dispatch ran at — bf16 or int8)."""
        with self._lock:
            self._requests += len(request_latencies_s)
            self._examples += n_real
            self._batches += 1
            self._rows += bucket
            self._dispatch_s += dispatch_s
            self._lat.extend(request_latencies_s)
            self._totals["requests"] += len(request_latencies_s)
            self._totals["examples"] += n_real
            self._hist_for("dispatch_seconds", precision).observe(dispatch_s)
            lat_h = self._hist_for("request_latency_seconds", precision)
            for lat in request_latencies_s:
                lat_h.observe(lat)
            if queue_waits_s is not None:
                self._qwait.extend(queue_waits_s)
                qw_h = self._hist_for("queue_wait_seconds", precision)
                for qw in queue_waits_s:
                    self._queue_wait_s += qw
                    qw_h.observe(qw)

    def observe_shed(self, n_requests: int = 1) -> None:
        """Count a request rejected by backpressure (`Overloaded`, HTTP
        429). Shed rate = shed / (requests + shed) is the third number of
        the load contract next to sustained QPS and p99-under-load
        (bench_serve.py --load): a server meeting its p99 by shedding half
        its offered traffic is not meeting anything."""
        with self._lock:
            self._shed += n_requests
            self._totals["shed"] += n_requests

    def _bump(self, interval_attr: str, total_key: str) -> None:
        with self._lock:
            setattr(self, interval_attr, getattr(self, interval_attr) + 1)
            self._totals[total_key] += 1

    def observe_admission_reject(self) -> None:
        """A request refused at the door because the dispatch-time EMA x
        queue depth said its deadline was unmeetable (fast 503 +
        Retry-After) — overload evidence for the autoscaler, same as shed."""
        self._bump("_admission_rejected", "admission_rejected")

    def observe_deadline_expired(self) -> None:
        """An ACCEPTED request whose result did not arrive by its deadline
        (HTTP 504): the admission estimate was too optimistic, or a
        dispatch stalled."""
        self._bump("_deadline_expired", "deadline_expired")

    def observe_breaker_reject(self) -> None:
        """A request failed fast because the model's circuit is open."""
        self._bump("_breaker_rejected", "breaker_rejected")

    def observe_dispatch_error(self) -> None:
        """A device dispatch raised (the whole batch's futures got the
        exception) — the circuit breaker's failure evidence."""
        self._bump("_dispatch_errors", "dispatch_errors")

    def observe_observer_error(self) -> None:
        """The per-batch observer tap raised — counted, never silent
        (each distinct error also gets one resilience event)."""
        self._bump("_observer_errors", "observer_errors")

    def totals(self) -> dict:
        """Lifetime counters, NEVER reset — the autoscale control loop
        samples deltas of these so a concurrent `snapshot(reset=True)`
        (the server's periodic flush) cannot zero its evidence window."""
        with self._lock:
            return dict(self._totals)

    def histograms(self) -> dict:
        """Lifetime latency/queue-wait/dispatch histograms AGGREGATED over
        precisions, in exposition shape ({name: {"buckets": [(le, cum)],
        "sum", "count"}}) — never reset, so scrapes are monotone."""
        with self._lock:
            out = {}
            for name, by_precision in self._hist.items():
                agg = _Histogram()
                for h in by_precision.values():
                    for i, n in enumerate(h.counts):
                        agg.counts[i] += n
                    agg.sum += h.sum
                    agg.count += h.count
                out[name] = agg.render()
            return out

    def histograms_by_precision(self) -> dict:
        """The labeled view `GET /metrics` renders: {name: {precision:
        exposition dict}} — one Prometheus series per (model, precision),
        so the int8-vs-bf16 dispatch/latency split is scrapeable."""
        with self._lock:
            return {name: {p: h.render() for p, h in by_precision.items()}
                    for name, by_precision in self._hist.items()}

    def snapshot(self, queue_depth: Optional[int] = None,
                 reset: bool = False) -> dict:
        """Metric dict (floats only — MetricsLogger-ready). `reset=True`
        zeroes the counters afterwards, making consecutive snapshots
        per-interval rates (the server's periodic flush; /stats leaves the
        counters alone)."""
        with self._lock:
            now = time.monotonic()
            dt = max(now - self._t0, 1e-9)
            out = {
                "requests": float(self._requests),
                "images_per_sec": self._examples / dt,
                "batches_per_sec": self._batches / dt,
                "mean_batch_fill": (self._examples / self._batches
                                    if self._batches else 0.0),
                "padding_waste": ((self._rows - self._examples) / self._rows
                                  if self._rows else 0.0),
                "mean_dispatch_ms": (1000.0 * self._dispatch_s / self._batches
                                     if self._batches else 0.0),
                # queueing share of latency (submit accept -> dispatch
                # start), distinct from the device's mean_dispatch_ms
                "mean_queue_wait_ms": (1000.0 * self._queue_wait_s
                                       / self._requests
                                       if self._requests else 0.0),
                "shed_requests": float(self._shed),
                "admission_rejected": float(self._admission_rejected),
                "deadline_expired": float(self._deadline_expired),
                "breaker_rejected": float(self._breaker_rejected),
                "dispatch_errors": float(self._dispatch_errors),
                "observer_errors": float(self._observer_errors),
            }
            if self._lat:
                lat_ms = np.asarray(self._lat, np.float64) * 1000.0
                out["p50_ms"] = float(np.percentile(lat_ms, 50))
                out["p99_ms"] = float(np.percentile(lat_ms, 99))
            if self._qwait:
                qw_ms = np.asarray(self._qwait, np.float64) * 1000.0
                out["p50_queue_ms"] = float(np.percentile(qw_ms, 50))
                out["p99_queue_ms"] = float(np.percentile(qw_ms, 99))
            if queue_depth is not None:
                out["queue_depth"] = float(queue_depth)
            if reset:
                self._reset_locked(now)
        return out
