"""Inference server: engine + batcher + metrics + graceful lifecycle.

`InferenceServer.serve()` runs a stdlib `ThreadingHTTPServer` (no new
dependencies — each connection gets a thread, and concurrent handler
threads are exactly the concurrency the micro-batcher coalesces):

    POST /predict   {"instances": [[...HWC floats...], ...]}
                    -> 200 {"predictions": [...]}   (f32 model outputs)
                    -> 400 bad shape/body, 429 overloaded (backpressure),
                       503 draining
    GET  /healthz   -> 200 {"status": "ok"|"draining", ...}
    GET  /stats     -> 200 cumulative ServingMetrics snapshot + queue depth

Graceful drain reuses the resilience SIGTERM/SIGINT contract
(core/resilience.GracefulShutdown — same handler the trainer installs):
the first signal stops the accept path (new submits get 503), every
request already accepted finishes and is answered, metrics flush, and the
process exits 0 — a preempted serving replica under a grace window answers
everything it promised and leaves cleanly. A second signal aborts
immediately, same as training.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import jax
import numpy as np

from ..core.metrics import MetricsLogger
from ..core.resilience import GracefulShutdown
from .batcher import Draining, DynamicBatcher, Overloaded
from .engine import PredictEngine
from .metrics import ServingMetrics

DRAIN_WHAT = ("finishing in-flight batches, rejecting new work, "
              "then exiting 0")


class InferenceServer:
    """Owns the serving stack's lifecycle; `serve()` blocks until a signal
    (or `stop()`), drains, and returns the final metrics snapshot."""

    def __init__(self, engine: PredictEngine, *,
                 max_batch: Optional[int] = None,
                 max_delay_ms: float = 5.0,
                 max_queue_examples: int = 1024,
                 workdir: Optional[str] = None,
                 flush_every_s: float = 10.0):
        self.engine = engine
        self.metrics = ServingMetrics()
        self.batcher = DynamicBatcher(
            engine, max_batch=max_batch, max_delay_ms=max_delay_ms,
            max_queue_examples=max_queue_examples, metrics=self.metrics)
        # same stream as the trainer: JSONL + TB when a workdir is given,
        # console echo always (MetricsLogger is the one logging mechanism)
        self.logger = MetricsLogger(workdir, name="serve")
        self.flush_every_s = flush_every_s
        self._flush_step = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.ready = threading.Event()   # set once the listener is bound
        self.bound_port: Optional[int] = None

    # -- metrics -----------------------------------------------------------

    def flush_metrics(self, echo: bool = True, reset: bool = True) -> dict:
        """Flush one per-interval snapshot to the metrics stream."""
        self._flush_step += 1
        snap = self.metrics.snapshot(queue_depth=self.batcher.queue_depth,
                                     reset=reset)
        self.logger.log(self._flush_step, snap, prefix="serve_", echo=echo)
        return snap

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        """Programmatic equivalent of one SIGTERM (tests/embedding use)."""
        self._stop.set()
        self._wake.set()

    def drain(self) -> dict:
        """Reject new work, finish everything accepted, flush metrics."""
        print(f"[serve:{self.engine.name}] graceful drain: rejecting new "
              f"work, finishing {self.batcher.queue_depth} queued examples",
              flush=True)
        self.batcher.drain()
        return self.flush_metrics(reset=False)

    def close(self) -> None:
        self.batcher.drain()
        self.logger.close()

    def serve(self, port: int = 8700, host: str = "127.0.0.1") -> dict:
        httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.bound_port = httpd.server_address[1]
        http_thread = threading.Thread(target=httpd.serve_forever,
                                       daemon=True, name="http-serve")
        with GracefulShutdown(on_signal=self._wake.set,
                              what=DRAIN_WHAT) as gs:
            http_thread.start()
            self.ready.set()
            print(f"[serve:{self.engine.name}] listening on "
                  f"http://{host}:{self.bound_port} "
                  f"buckets={list(self.engine.buckets)} "
                  f"max_delay_ms={self.batcher.max_delay * 1000:g}",
                  flush=True)
            while not (gs.requested or self._stop.is_set()):
                if self._wake.wait(self.flush_every_s):
                    self._wake.clear()   # signal/stop — re-check the flag
                    continue
                self.flush_metrics()     # quiet period: periodic flush
            # drain FIRST: handlers blocked on accepted futures still get
            # their answers while new submits 503; only then stop accepting
            # connections at all
            snap = self.drain()
            httpd.shutdown()
            httpd.server_close()
            http_thread.join(timeout=10)
        print(f"[serve:{self.engine.name}] drained cleanly", flush=True)
        return snap


def _make_handler(server: InferenceServer):
    class Handler(BaseHTTPRequestHandler):
        # per-request stderr lines are pure noise under load; the metrics
        # stream is the observability surface
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _json(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._json(200, {
                    "status": ("draining" if server.batcher.draining
                               else "ok"),
                    "model": server.engine.name,
                    "buckets": list(server.engine.buckets),
                    "max_batch": server.batcher.max_batch,
                    # weight provenance (checkpoint epoch + integrity-
                    # manifest hash + verified flag): diff it across
                    # replicas to audit a fleet for weight skew
                    "weights": server.engine.provenance,
                })
            elif self.path == "/stats":
                self._json(200, {
                    **server.metrics.snapshot(
                        queue_depth=server.batcher.queue_depth),
                    "weights": server.engine.provenance,
                })
            else:
                self._json(404, {"error": f"unknown path {self.path!r}"})

        def do_POST(self):
            if self.path != "/predict":
                return self._json(404, {"error": f"unknown path "
                                                 f"{self.path!r}"})
            try:
                length = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(length) or b"{}")
                x = np.asarray(payload["instances"], np.float32)
            except (KeyError, TypeError, ValueError) as e:
                return self._json(400, {
                    "error": f"body must be JSON {{'instances': "
                             f"[...]}}: {e}"})
            try:
                fut = server.batcher.submit(x)
            except Overloaded as e:
                return self._json(429, {"error": str(e)})
            except Draining as e:
                return self._json(503, {"error": str(e)})
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            try:
                out = fut.result(timeout=120)
            except Exception as e:  # noqa: BLE001 — a failed dispatch must
                return self._json(500, {"error": repr(e)})  # not hang the client
            self._json(200, {"predictions": jax.tree_util.tree_map(
                lambda a: np.asarray(a).tolist(), out)})

    return Handler
