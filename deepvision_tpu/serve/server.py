"""Inference server: fleet + batchers + metrics + reload + lifecycle.

`InferenceServer.serve()` runs a stdlib `ThreadingHTTPServer` (no new
dependencies — each connection gets a thread, and concurrent handler
threads are exactly the concurrency the micro-batchers coalesce) over a
`ModelFleet` (serve/fleet.py) — one model or many behind one process:

    POST /predict           {"instances": [[...HWC floats...], ...],
                             "deadline_ms": 250}   (deadline optional)
                            -> 200 {"predictions": [...]} from the DEFAULT
                               model (f32 outputs; the PR 3 surface)
    POST /predict/<model>   -> same, routed by registry name; an unknown
                               name gets 404 with "served_models" in the
                               body (never an opaque error)
                            -> 400 bad shape/body, 429 overloaded
                               (per-model backpressure)
                            -> 503 + Retry-After: admission control
                               (deadline unmeetable given the dispatch EMA
                               and queue), circuit open (K consecutive
                               dispatch errors — body names the model), or
                               draining
                            -> 504 deadline expired AFTER acceptance — the
                               wait is deadline-bounded (client
                               "deadline_ms" or the --deadline-ms default),
                               never the old blind 120 s
    GET  /healthz           -> 200 aggregate status + per-model weight
                               provenance (epoch, manifest hash, verified),
                               reload outcomes, worker count, autoscale
                               decisions, breaker state, and the mesh axis
                               (axis names x sizes + per-chip weight
                               bytes when GSPMD-sharded) — diff across
                               replicas to audit a fleet for weight skew
    GET  /stats[/<model>]   -> 200 per-model ServingMetrics snapshot(s)
    GET  /metrics           -> 200 Prometheus text exposition (0.0.4):
                               lifetime counters, queue-depth/worker/breaker
                               gauges, fixed-bucket latency histograms —
                               `model`-labeled, monotone across scrapes
                               (docs/OBSERVABILITY.md)
    GET  /trace[?secs=N]    -> 200 Chrome trace-event JSON of the recent
                               span ring (last N seconds; default all) —
                               load in Perfetto to follow one request
                               admission -> queue -> batch -> dispatch ->
                               response
    POST /reload            -> 200 after ONE synchronous hot-reload sweep
                               (WeightReloader.check_once): new verified
                               epochs swap in — or run the full
                               shadow/canary promotion pipeline when one
                               is attached — before the response, which
                               carries the outcome. The tier router's
                               rolling promotion (serve/tier.py) drives
                               replicas one at a time through this

Request ids: every request gets one — the client's `X-Request-Id` header
when present, a generated id otherwise — echoed in EVERY response
(200/400/429/503/504), stamped into the request's spans, and carried on
any `resilience_*` event the request triggers, so a shed or expiry can be
joined to the exact spans (and client log line) behind it. Client-supplied
ids force trace sampling: the request an operator is chasing always
leaves its spans.

Overload control (docs/SERVING.md "Overload control"): when
`autoscale_every_s > 0` a control loop samples per-model shed/p99/queue
signals and resizes each model's dispatcher pool between `workers` and
`max_workers` — scaling up is a thread + a reference to the shared AOT
bucket cache, zero recompiles.

Hot weight reload (serve/reload.py): models constructed with a workdir are
watched for new integrity-verified epochs, which swap in atomically with
zero downtime and zero recompiles; `reload_every_s > 0` arms the poller.

Graceful drain reuses the resilience SIGTERM/SIGINT contract
(core/resilience.GracefulShutdown — same handler the trainer installs):
the first signal flips /healthz to "draining" IN the signal handler —
strictly before any work is refused — then (after `drain_grace_s`, the
window that lets a router's health poll de-admit this replica while it
still answers everything) stops the accept path (new submits get 503),
finishes and answers every request already accepted, stops the reloader,
flushes metrics, and exits 0 — a preempted serving replica under a grace
window answers everything it promised and leaves cleanly. A second signal
aborts immediately, same as training.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import jax
import numpy as np

from ..core.metrics import MetricsLogger
from ..core.resilience import GracefulShutdown, log_resilience_event
from ..obs.export import chrome_trace, render_prometheus
from ..obs.trace import Tracer, new_request_id
from ..utils.faults import FaultInjector
from .autoscale import AutoscaleController
from .batcher import (CircuitOpen, DeadlineExpired, DeadlineUnmeetable,
                      Draining, Overloaded, result_within)
from .engine import PredictEngine
from .fleet import ModelFleet, UnknownModel
from .reload import WeightReloader

DRAIN_WHAT = ("finishing in-flight batches, rejecting new work, "
              "then exiting 0")

# HTTP-wait bound for requests that carry no deadline and hit a model with
# no configured default: generous enough for a cold first dispatch on a
# slow host, but BOUNDED — the old blind 120 s wait is gone everywhere
FALLBACK_DEADLINE_S = 30.0


class InferenceServer:
    """Owns the serving stack's lifecycle; `serve()` blocks until a signal
    (or `stop()`), drains, and returns the final metrics snapshot.

    Construct with a single `engine` (the PR 3 surface — a one-model fleet
    is built around it) or a pre-built multi-model `fleet`; `engine`,
    `batcher`, and `metrics` always alias the DEFAULT model so existing
    single-model callers read the same attributes they always did."""

    def __init__(self, engine: Optional[PredictEngine] = None, *,
                 fleet: Optional[ModelFleet] = None,
                 max_batch: Optional[int] = None,
                 max_delay_ms: float = 5.0,
                 max_queue_examples: int = 1024,
                 workdir: Optional[str] = None,
                 flush_every_s: float = 10.0,
                 reload_every_s: float = 0.0,
                 log_dir: Optional[str] = None,
                 promote_gate: Optional[float] = None,
                 canary_frac: float = 0.05,
                 canary_window_s: float = 5.0,
                 workers: int = 1,
                 max_workers: int = 4,
                 autoscale_every_s: float = 0.0,
                 flywheel_every_s: float = 0.0,
                 default_deadline_s: Optional[float] = None,
                 breaker_k: int = 5,
                 breaker_cooldown_s: float = 5.0,
                 trace: bool = True,
                 trace_sample: Optional[float] = None,
                 trace_capacity: int = 16384,
                 drain_grace_s: float = 0.0,
                 replica_id: Optional[str] = None,
                 faults: Optional[FaultInjector] = None):
        if (engine is None) == (fleet is None):
            raise ValueError("pass exactly one of engine= or fleet=")
        if fleet is None:
            fleet = ModelFleet()
            fleet.add(engine, workdir=workdir, max_batch=max_batch,
                      max_delay_ms=max_delay_ms,
                      max_queue_examples=max_queue_examples,
                      workers=workers,
                      default_deadline_s=default_deadline_s,
                      breaker_k=breaker_k,
                      breaker_cooldown_s=breaker_cooldown_s)
        self.fleet = fleet
        self.default_deadline_s = default_deadline_s
        default = fleet.default
        self.engine = default.engine
        self.batcher = default.batcher
        self.metrics = default.metrics
        # same stream as the trainer: JSONL + TB when a workdir is given,
        # console echo always (MetricsLogger is the one logging mechanism)
        self.logger = MetricsLogger(log_dir or workdir, name="serve")
        if promote_gate is not None:
            # accuracy-gated promotion (serve/promote.py): candidates run
            # shadow eval + canary before going live; hot reload delegates
            # its swap decision to the attached controllers
            from .promote import attach_promoters
            attach_promoters(fleet, gate_min_delta=promote_gate,
                             canary_frac=canary_frac,
                             canary_window_s=canary_window_s,
                             logger=self.logger,
                             warn=lambda msg: print(msg, flush=True))
        self.reloader = WeightReloader(
            fleet, poll_every_s=reload_every_s, logger=self.logger)
        # end-to-end tracing (obs/trace.py): one tracer behind /trace,
        # shared by the HTTP handlers (request/admission/response spans)
        # and every model's dispatcher (queue_wait/batch/dispatch spans).
        # `trace=False` disables it outright — every producer is behind a
        # single branch, so the hot path pays ~zero.
        self.tracer = Tracer(capacity=trace_capacity, sample=trace_sample,
                             enabled=trace)
        self._event_lock = threading.Lock()
        self._event_seq = 0
        # overload-control wiring: every batcher/breaker logs onto the
        # server's resilience_ stream (observer-tap errors, breaker
        # transitions are incident lines, not stderr-only)
        for sm in fleet:
            sm.batcher.logger = self.logger
            sm.batcher.tracer = self.tracer
            if sm.breaker is not None:
                sm.breaker.logger = self.logger
        # shed-driven autoscaling (serve/autoscale.py): armed by
        # autoscale_every_s > 0, scales each model's dispatcher pool
        # between its startup worker count and max_workers
        self.autoscaler = AutoscaleController(
            list(fleet), interval_s=autoscale_every_s,
            min_workers=min(sm.batcher.workers for sm in fleet),
            max_workers=max(max_workers,
                            max(sm.batcher.workers for sm in fleet)),
            logger=self.logger)
        self.flush_every_s = flush_every_s
        self._flush_step = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.ready = threading.Event()   # set once the listener is bound
        self.bound_port: Optional[int] = None
        # the DE-ADMISSION flag: set the INSTANT a drain is requested
        # (signal handler / stop()), strictly BEFORE the batcher drain
        # starts rejecting work. /healthz flips to "draining" off this
        # flag, so a router polling health de-admits the replica while it
        # is still answering everything — without it the first evidence of
        # shutdown a router saw was 503s (the bug this flag fixes).
        self.draining_flag = threading.Event()
        # drain grace: how long a drain-requested server keeps accepting
        # (and answering) normally after flipping /healthz, so routers get
        # at least one health-poll interval to stop sending before submits
        # start answering 503 Draining. 0 = flip and drain immediately
        # (the single-process default; the tier replica sets a real grace)
        self.drain_grace_s = float(drain_grace_s)
        # identity within a replica tier (serve/tier.py): echoed on
        # /healthz so the router can confirm it is talking to the replica
        # it thinks it is (a respawned process keeps its slot's id)
        self.replica_id = replica_id
        # replica-level fault injection (utils/faults.py REPLICA_CRASH /
        # REPLICA_WEDGE): consulted at the top of every HTTP request —
        # inert injectors cost two None-compares per request
        self.faults = faults if faults is not None else FaultInjector.from_env()
        # drift-triggered continuous training (flywheel/): armed by
        # flywheel_every_s > 0, one controller per promotion-gated,
        # workdir-backed model. Shares the server's logger (resilience_
        # stream), tracer (episode spans beside request spans), and fault
        # injector (DEEPVISION_FAULT_DRIFT_SHIFT rehearsals).
        self.flywheels: list = []
        if flywheel_every_s > 0:
            from ..flywheel.controller import attach_flywheels
            attach_flywheels(fleet, logger=self.logger, tracer=self.tracer,
                             tick_every_s=flywheel_every_s,
                             faults=self.faults,
                             warn=lambda msg: print(msg, flush=True))
            self.flywheels = [sm.flywheel for sm in fleet
                              if sm.flywheel is not None]

    # -- metrics -----------------------------------------------------------

    def next_event_step(self) -> int:
        """Monotone step counter for per-request resilience events (sheds,
        expiries) logged from concurrent handler threads."""
        with self._event_lock:
            self._event_seq += 1
            return self._event_seq

    def flush_metrics(self, echo: bool = True, reset: bool = True) -> dict:
        """Flush one per-interval snapshot per model to the metrics stream;
        returns the default model's (a one-model fleet keeps the PR 3
        stream shape: bare `serve_` keys)."""
        self._flush_step += 1
        single = len(self.fleet) == 1
        out: dict = {}
        for sm in self.fleet:
            snap = sm.metrics.snapshot(queue_depth=sm.batcher.queue_depth,
                                       reset=reset)
            prefix = "serve_" if single else f"serve_{sm.name}_"
            self.logger.log(self._flush_step, snap, prefix=prefix, echo=echo)
            if sm is self.fleet.default:
                out = snap
        return out

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        """Programmatic equivalent of one SIGTERM (tests/embedding use)."""
        self.draining_flag.set()   # de-admit BEFORE the drain starts
        self._stop.set()
        self._wake.set()

    def drain(self) -> dict:
        """Stop reloading, reject new work, finish everything accepted,
        flush metrics. An in-flight promotion canary is aborted FIRST —
        the candidate rolls back to the incumbent and the poller thread
        (blocked in its canary window) unblocks, so the reloader join
        below doesn't wait out the window."""
        for sm in self.fleet:
            if sm.promoter is not None:
                sm.promoter.abort()
        for fw in self.flywheels:
            fw.stop()
        self.autoscaler.stop()
        self.reloader.stop()
        print(f"[serve:{self.engine.name}] graceful drain: rejecting new "
              f"work, finishing {self.fleet.queue_depth} queued examples "
              f"across {len(self.fleet)} model(s)", flush=True)
        self.fleet.drain()
        return self.flush_metrics(reset=False)

    def close(self) -> None:
        for fw in self.flywheels:
            fw.stop()
        self.autoscaler.stop()
        self.reloader.stop()
        self.fleet.drain()
        self.logger.close()

    def serve(self, port: int = 8700, host: str = "127.0.0.1") -> dict:
        httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.bound_port = httpd.server_address[1]
        http_thread = threading.Thread(target=httpd.serve_forever,
                                       daemon=True, name="http-serve")

        def on_signal() -> None:
            # ordering is the de-admission contract: the draining flag (and
            # with it /healthz) flips IN the signal handler, before the
            # main loop has even woken to start the batcher drain
            self.draining_flag.set()
            self._wake.set()

        with GracefulShutdown(on_signal=on_signal,
                              what=DRAIN_WHAT) as gs:
            self.reloader.start()
            self.autoscaler.start()
            for fw in self.flywheels:
                fw.start()
            http_thread.start()
            self.ready.set()
            print(f"[serve:{self.engine.name}] listening on "
                  f"http://{host}:{self.bound_port} "
                  f"models={self.fleet.names()} "
                  f"default={self.engine.name} "
                  f"max_delay_ms={self.batcher.max_delay * 1000:g}",
                  flush=True)
            while not (gs.requested or self._stop.is_set()):
                if self._wake.wait(self.flush_every_s):
                    self._wake.clear()   # signal/stop — re-check the flag
                    continue
                self.flush_metrics()     # quiet period: periodic flush
            # de-admission grace: /healthz already says "draining" (the
            # signal handler flipped it), and during this window the server
            # still ACCEPTS and answers everything — a router polling
            # health stops sending new work before a single submit is
            # refused, so a graceful replica shutdown costs zero 5xx
            self.draining_flag.set()   # idempotent (stop() also sets it)
            if self.drain_grace_s > 0:
                time.sleep(self.drain_grace_s)
            # drain FIRST: handlers blocked on accepted futures still get
            # their answers while new submits 503; only then stop accepting
            # connections at all
            snap = self.drain()
            httpd.shutdown()
            httpd.server_close()
            http_thread.join(timeout=10)
        print(f"[serve:{self.engine.name}] drained cleanly", flush=True)
        return snap


def _make_handler(server: InferenceServer):
    class Handler(BaseHTTPRequestHandler):
        # per-request stderr lines are pure noise under load; the metrics
        # stream is the observability surface
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        # the request id assigned by the current do_GET/do_POST — echoed
        # on EVERY response this handler writes, refusals included
        request_id: Optional[str] = None

        def _assign_request_id(self) -> str:
            self.request_id = (self.headers.get("X-Request-Id")
                               or new_request_id())
            return self.request_id

        def _send(self, code: int, body: bytes, ctype: str,
                  headers=None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            if self.request_id is not None:
                self.send_header("X-Request-Id", self.request_id)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _json(self, code: int, obj, headers=None) -> None:
            self._send(code, json.dumps(obj).encode(), "application/json",
                       headers)

        def _resolve(self, root: str):
            """Map `/<root>` or `/<root>/<model>` to a ServedModel; answers
            the 404 (with the served-model list) itself and returns None
            when the path doesn't resolve."""
            name = None
            if self.path != root:
                if not self.path.startswith(root + "/"):
                    return self._unknown_path()
                name = self.path[len(root) + 1:]
            try:
                return server.fleet.get(name)
            except UnknownModel as e:
                self._json(404, {"error": str(e),
                                 "served_models": e.served})
                return None

        def _unknown_path(self) -> None:
            self._json(404, {"error": f"unknown path {self.path!r}",
                             "served_models": server.fleet.names()})

        def do_GET(self):
            server.faults.on_replica_request(predict=False)
            self._assign_request_id()
            if self.path == "/metrics":
                # Prometheus text exposition: counters come from lifetime
                # stores, so consecutive scrapes are monotone
                return self._send(
                    200, render_prometheus(server.fleet).encode(),
                    "text/plain; version=0.0.4; charset=utf-8")
            if self.path == "/trace" or self.path.startswith("/trace?"):
                q = urllib.parse.parse_qs(
                    urllib.parse.urlparse(self.path).query)
                try:
                    secs = float(q["secs"][0]) if "secs" in q else None
                except ValueError:
                    return self._json(400, {"error": "secs must be a "
                                                     "number"})
                return self._json(200, chrome_trace(server.tracer, secs))
            if self.path == "/healthz":
                d = server.fleet.default
                self._json(200, {
                    # de-admission ordering: the draining flag flips in the
                    # signal handler, BEFORE the batcher drain starts — a
                    # router sees "draining" while the replica still
                    # answers everything (the fix pinned by test_tier's
                    # drain-under-router-traffic test)
                    "status": ("draining"
                               if (server.draining_flag.is_set()
                                   or server.fleet.draining)
                               else "ok"),
                    # identity + load signals the tier router's
                    # least-loaded routing reads (serve/tier.py)
                    "replica": server.replica_id,
                    "queue_depth": server.fleet.queue_depth,
                    # default-model fields first, exactly the PR 3 shape —
                    # single-model probes keep working unchanged
                    "model": d.name,
                    "buckets": list(d.engine.buckets),
                    "max_batch": d.batcher.max_batch,
                    "weights": d.engine.provenance,
                    # the int8 serving axis: active precision + the last
                    # quant-gate decision (docs/SERVING.md "Quantized
                    # serving") — a refused gate is visible HERE, not
                    # buried in stderr
                    "precision": getattr(d.engine, "precision", "bf16"),
                    "quant": getattr(d.engine, "quant_decision", None),
                    # the mesh serving axis beside it: axis names x sizes
                    # when the engine is GSPMD-sharded (None = one chip)
                    # and the per-chip weight-byte accounting — provenance
                    # also carries "mesh" + "resharded", so one /healthz
                    # shows which checkpoints crossed a topology to get
                    # here (docs/SERVING.md "Mesh serving")
                    "mesh": getattr(d.engine, "mesh_axes", None),
                    "weight_bytes_per_chip": (
                        d.engine.weight_bytes_per_chip()
                        if hasattr(d.engine, "weight_bytes_per_chip")
                        else None),
                    # the fleet view: per-model weight provenance
                    # (checkpoint epoch + integrity-manifest hash +
                    # verified flag) and reload outcomes — diff across
                    # replicas to audit a fleet for weight skew
                    "served_models": server.fleet.names(),
                    "models": server.fleet.describe(),
                })
            elif self.path == "/stats" or self.path.startswith("/stats/"):
                sm = self._resolve("/stats")
                if sm is None:
                    return
                snap = sm.snapshot()
                if self.path == "/stats":
                    snap["models"] = server.fleet.snapshots()
                self._json(200, snap)
            else:
                self._unknown_path()

        def do_POST(self):
            rid = self._assign_request_id()
            if self.path == "/reload":
                # tier control plane (serve/tier.py rolling promotion): run
                # ONE synchronous reload sweep — new verified epochs swap
                # in (or run the full shadow/canary pipeline when a
                # promoter is attached) before this returns, so the caller
                # reads the outcome from the response instead of polling
                server.faults.on_replica_request(predict=False)
                try:
                    swapped = server.reloader.check_once()
                except Exception as e:  # noqa: BLE001 — control plane must
                    return self._json(500, {"error": repr(e)})   # answer
                return self._json(200, {
                    "swapped": swapped,
                    "watched": [sm.name for sm in server.reloader.models],
                    "models": server.fleet.describe(),
                })
            server.faults.on_replica_request(
                predict=self.path.startswith("/predict"))
            sm = (self._resolve("/predict")
                  if self.path.startswith("/predict") else
                  self._unknown_path())
            if sm is None:
                return
            t_in = time.monotonic()
            tracer = server.tracer
            # sampling decision for this request's spans: a client-supplied
            # X-Request-Id forces it (the one-request-debugging contract);
            # ctx is None for unsampled requests — zero spans recorded
            ctx = tracer.request_context(
                rid, forced="X-Request-Id" in self.headers)

            def refused(outcome: str, admission: bool = True) -> None:
                """A request turned away (429/503/504): when sampled, close
                its span chain and log ONE correlated resilience event, so
                the shed joins to the exact spans that led to it.
                `admission=False` for post-acceptance failures (504), whose
                admission span was already recorded as accepted."""
                if ctx is None:
                    return
                now = time.monotonic()
                if admission:
                    tracer.add("admission", "serve", int(t_adm * 1e9),
                               int((now - t_adm) * 1e9),
                               args={"request_id": rid, "model": sm.name,
                                     "outcome": outcome})
                tracer.add("http_request", "serve", int(t_in * 1e9),
                           int((now - t_in) * 1e9),
                           args={"request_id": rid, "model": sm.name,
                                 "outcome": outcome},
                           span_id=ctx.root_id)
                log_resilience_event(
                    server.logger, server.next_event_step(),
                    {f"serve_refused_{outcome}": 1.0},
                    request_id=rid, trace_ref=ctx.trace_ref)

            try:
                length = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(length) or b"{}")
                x = np.asarray(payload["instances"], np.float32)
                # per-request precision override ('bf16'/'int8'; absent =
                # the model's active precision). Validated at submit — an
                # unarmed precision answers 400 naming the gate.
                precision = payload.get("precision")
                if precision is not None and precision not in ("bf16",
                                                               "int8"):
                    raise ValueError(
                        f"precision must be 'bf16' or 'int8', got "
                        f"{precision!r}")
                # request deadline: body "deadline_ms", else the
                # X-Deadline-Ms header, else the model's configured
                # default, else the server fallback — ALWAYS bounded
                deadline_ms = payload.get(
                    "deadline_ms", self.headers.get("X-Deadline-Ms"))
                if deadline_ms is not None:
                    deadline_s = float(deadline_ms) / 1000.0
                    if deadline_s <= 0:
                        raise ValueError(
                            f"deadline_ms must be > 0, got {deadline_ms}")
                else:
                    deadline_s = (sm.batcher.default_deadline_s
                                  or server.default_deadline_s
                                  or FALLBACK_DEADLINE_S)
            except (KeyError, TypeError, ValueError) as e:
                return self._json(400, {
                    "error": f"body must be JSON {{'instances': [...]"
                             f"[, 'deadline_ms': N]}}: {e}"})
            t_adm = time.monotonic()
            try:
                # routes through the promotion controller when one is
                # attached: the canary fraction runs on the candidate
                # generation, everything else on the live weights.
                # Admission control, backpressure, and the circuit
                # breaker all refuse HERE, before anything is queued.
                fut, generation = sm.submit_routed(
                    x, deadline_s=deadline_s, precision=precision,
                    trace=ctx)
                # pin the responding generation's weight epoch NOW, at
                # routing time — a concurrent promote flipping the live
                # reference later must not relabel this response
                gen_prov = (sm.engine.candidate_provenance
                            if (generation == "candidate"
                                and sm.engine.candidate_provenance)
                            else sm.engine.provenance)
                weights_epoch = gen_prov.get("checkpoint_epoch")
                if ctx is not None:
                    tracer.add("admission", "serve", int(t_adm * 1e9),
                               int((time.monotonic() - t_adm) * 1e9),
                               args={"request_id": rid, "model": sm.name,
                                     "outcome": "accepted"})
            except Overloaded as e:
                refused("overloaded")
                return self._json(429, {"error": str(e)})
            except DeadlineUnmeetable as e:
                # fast 503: the queue says this deadline cannot be met —
                # Retry-After tells the client when the backlog should
                # have cleared
                refused("deadline_unmeetable")
                return self._json(
                    503, {"error": str(e), "model": sm.name,
                          "reason": "deadline_unmeetable",
                          "eta_ms": round(e.eta_s * 1000.0, 1)},
                    headers={"Retry-After":
                             f"{max(e.retry_after_s, 0.001):.3f}"})
            except CircuitOpen as e:
                # fail-fast 503 NAMING the model whose dispatch path is
                # broken — the fleet's other models keep serving
                refused("circuit_open")
                return self._json(
                    503, {"error": str(e), "model": e.model,
                          "reason": "circuit_open"},
                    headers={"Retry-After":
                             f"{max(e.retry_after_s, 0.001):.3f}"})
            except Draining as e:
                refused("draining")
                return self._json(503, {"error": str(e),
                                        "reason": "draining"})
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            try:
                # deadline-bounded wait: 504 on expiry, never a blind
                # multi-minute block — a wedged model answers in seconds
                out = result_within(
                    fut, max(0.001, t_in + deadline_s - time.monotonic()),
                    what=f"predict[{sm.name}]")
            except DeadlineExpired as e:
                sm.metrics.observe_deadline_expired()
                refused("deadline_expired", admission=False)
                return self._json(504, {"error": str(e), "model": sm.name,
                                        "reason": "deadline_expired",
                                        "deadline_ms":
                                            round(deadline_s * 1000.0, 1)})
            except Exception as e:  # noqa: BLE001 — a failed dispatch must
                refused("dispatch_error", admission=False)  # not hang the
                return self._json(500, {"error": repr(e)})  # client
            # every 200 reports the weight generation that answered it
            # ("live"/"candidate" + that generation's checkpoint epoch):
            # the tier's no-mixed-generation audit reads this per response
            body = {"predictions": jax.tree_util.tree_map(
                        lambda a: np.asarray(a).tolist(), out),
                    "generation": generation,
                    "weights_epoch": weights_epoch}
            if ctx is None:
                return self._json(200, body)
            t_w = time.monotonic()
            self._json(200, body)
            now = time.monotonic()
            tracer.add("response_write", "serve", int(t_w * 1e9),
                       int((now - t_w) * 1e9),
                       args={"request_id": rid, "model": sm.name})
            # root span last: its chain (admission -> queue_wait -> batch ->
            # device_dispatch -> response_write) all carries request_id
            tracer.add("http_request", "serve", int(t_in * 1e9),
                       int((now - t_in) * 1e9),
                       args={"request_id": rid, "model": sm.name,
                             "status": 200},
                       span_id=ctx.root_id)

    return Handler
