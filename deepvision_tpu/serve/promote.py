"""Accuracy-gated train→serve promotion: shadow eval, canary, auto-rollback.

The hot-reload path (serve/reload.py) promotes a candidate checkpoint on
*integrity* alone: a manifest that hashes clean ships straight to 100% of
traffic. That catches corrupt bytes, not a training run that quietly
regressed — a bad LR resume, a divergent epoch, a shard that rots into
plausible-but-wrong weights and still hashes exactly what was written. This
module closes that gap with the staged pipeline a millions-of-users
deployment actually runs, composed entirely from parts that already exist:
the engine can host two weight generations through one AOT bucket cache
(`PredictEngine.stage_candidate`, zero recompiles), the batcher never mixes
generations inside a batch (generation-tagged coalescing), and every
decision lands on the `resilience_` metrics stream (core/resilience.py).

Per candidate epoch, `PromotionController.propose` runs four stages:

1. **Shadow.** The verified candidate is staged beside the live weights —
   off the request path — and a PINNED eval shard is replayed against BOTH
   generations through the same compiled programs. The score is the
   family's watched metric (top-1 accuracy for classification, mIoU for
   segmentation — the same quantity `Trainer.fit` tracks as `watch`).
2. **Gate.** Promote only if `candidate - live >= gate_min_delta`
   (default: the candidate may not be more than 2 points worse). A refusal
   drops the candidate, logs a quarantine decision to the `resilience_`
   stream, and is CACHED by the reloader so the same bad epoch is never
   re-evaluated.
3. **Canary.** A configurable fraction of live traffic is routed to the
   candidate generation (`route()` tags submissions; the batcher builds
   per-generation batches) for a decision window, comparing canary vs
   baseline p99 and error rate.
4. **Promote or auto-rollback.** On success the reference flips fleet-wide
   (`promote_candidate` — the same one-assignment flip hot reload uses);
   on a p99/error regression — or a shutdown mid-canary — the controller
   retreats to the incumbent (`drop_candidate`). In-flight batches always
   finish on exactly one generation either way.

Deterministic failure injection for both negative paths:
`DEEPVISION_FAULT_PROMOTE_REGRESS=<epoch>:accuracy` degrades the
candidate's shadow score (the gate must refuse); `...=<epoch>:latency`
delays every candidate-generation dispatch (the canary comparison must
roll back). docs/FAILURES.md "Promotion decisions".
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..core import scoring
from ..core.resilience import log_resilience_event
from ..utils.faults import FaultInjector

# decisions `propose` can return, in the order the pipeline can take them
REFUSED_INCOMPATIBLE = "refused_incompatible"
REFUSED_GATE = "refused_gate"
ROLLED_BACK_CANARY = "rolled_back_canary"
ROLLED_BACK_ABORT = "rolled_back_abort"
PROMOTED = "promoted"

# families whose watched metric is computable from the engine's serving
# outputs — since core/scoring.py grew the detection/pose/centernet proxy
# scores (box-count agreement, PCK), that is every servable family; GANs
# have no single serving engine at all
GATED_FAMILIES = scoring.GATED_FAMILIES

# injected candidate-dispatch delay for the `latency` regression kind —
# large against any sane dispatch time so the canary comparison cannot
# miss it, small enough to keep tests fast
FAULT_LATENCY_SPIKE_S = 0.05
# the `accuracy` regression kind subtracts this from the candidate's
# shadow score: a deterministic stand-in for a regressed epoch that works
# regardless of how well the incumbent scores the pinned shard (shifting
# predictions would be invisible when the incumbent is near chance)
FAULT_ACCURACY_DROP = 0.5


def pinned_eval_shard(cfg, engine, *, examples: int = 64,
                      seed: int = scoring.DEFAULT_SHARD_SEED
                      ) -> Tuple[np.ndarray, tuple]:
    """The default pinned shadow-eval shard, `(images, targets)` from
    core/scoring.pinned_shard shaped/dtyped for this engine. Deterministic
    per (config, seed) down to the byte, so live and candidate generations
    are always scored on IDENTICAL inputs — the delta is pure weight
    difference. Production deployments pass a real held-out shard via
    `eval_batch=`; the synthetic default keeps the gate closed-loop
    testable (and preflight-able) with no data on disk."""
    try:
        return scoring.pinned_shard(
            cfg, image_size=engine.example_shape[0],
            input_dtype=engine.input_dtype, examples=examples, seed=seed)
    except ValueError:
        raise ValueError(
            f"config {cfg.name!r} (family {cfg.family!r}) has no "
            f"predict-side watch metric — accuracy-gated promotion "
            f"supports families {GATED_FAMILIES}; serve this model "
            f"without --promote-gate (integrity-verified hot reload "
            f"still applies)") from None


class PromotionController:
    """Owns one served model's promotion lifecycle. Attaches itself to the
    `ServedModel` (`sm.promoter`) and taps its batcher's per-batch observer
    for the canary comparison; the reloader calls `propose` with a
    verified, deserialized candidate instead of swapping directly.

    `propose` runs on the reloader's poller thread and blocks through the
    canary window — request threads only ever see the cheap `route()` call
    and per-batch observer appends. `abort()` (the server's drain path)
    interrupts a canary immediately and rolls back to the incumbent, so a
    SIGTERM mid-canary drains on exactly the weights that were live before
    the candidate appeared."""

    def __init__(self, sm, *,
                 gate_min_delta: float = -0.02,
                 canary_frac: float = 0.05,
                 canary_window_s: float = 5.0,
                 canary_min_requests: int = 8,
                 p99_factor: float = 1.5,
                 error_rate_delta: float = 0.02,
                 eval_batch: Optional[Tuple] = None,
                 eval_examples: int = 64,
                 logger=None,
                 faults: Optional[FaultInjector] = None,
                 history_limit: int = 32):
        if not 0.0 < canary_frac <= 1.0:
            raise ValueError(f"canary_frac must be in (0, 1], got "
                             f"{canary_frac}")
        if canary_window_s < 0:
            raise ValueError(f"canary_window_s must be >= 0, got "
                             f"{canary_window_s}")
        from ..configs import get_config
        self.sm = sm
        self.cfg = get_config(sm.name)
        if self.cfg.family not in GATED_FAMILIES:
            raise ValueError(
                f"config {sm.name!r} (family {self.cfg.family!r}) is not "
                f"promotion-gatable — supported families: {GATED_FAMILIES}")
        self.gate_min_delta = float(gate_min_delta)
        self.canary_frac = float(canary_frac)
        self.canary_window_s = float(canary_window_s)
        self.canary_min_requests = int(canary_min_requests)
        self.p99_factor = float(p99_factor)
        self.error_rate_delta = float(error_rate_delta)
        self.logger = logger
        self.faults = faults if faults is not None else FaultInjector.from_env()
        self._eval_batch = eval_batch
        self._eval_examples = int(eval_examples)
        self._history_limit = int(history_limit)

        self._lock = threading.Lock()
        self.state = "idle"            # idle | shadow | canary
        self.history: List[dict] = []  # newest-last decision records
        self._events = 0               # step counter for the metrics stream
        self._abort = threading.Event()
        self._route_acc = 0.0
        # canary window accumulators, reset per candidate
        self._obs: dict = {}
        self.shadow_evals = 0          # candidates shadow-scored (test hook)
        # set by the flywheel controller (flywheel/controller.py) around a
        # drift-triggered proposal: every decision record and resilience
        # event of that proposal carries the episode id, so the promotion
        # verdict joins the drift event and fine-tune spans on one key
        self.flywheel_id: Optional[str] = None

        # wire into the serving unit: routing + the per-batch canary tap
        sm.promoter = self
        sm.batcher.observer = self._observe

    # -- request-path hooks (cheap, called per request/batch) --------------

    def route(self) -> Optional[str]:
        """Which generation this request runs on: 'candidate' for the
        canary fraction while a canary is in flight, else None (live).
        Deterministic fractional accumulator, thread-safe."""
        if self.state != "canary":
            return None
        with self._lock:
            if self.state != "canary":
                return None
            self._route_acc += self.canary_frac
            if self._route_acc >= 1.0:
                self._route_acc -= 1.0
                return "candidate"
        return None

    def _observe(self, generation: str, latencies_s, dispatch_s,
                 error, sample=None) -> None:
        """Batcher per-batch tap: accumulate canary-window evidence —
        request latencies, per-batch dispatch times, error counts, each
        attributed to the generation that batch ran on. `sample` (the
        batch's input/output references) is the flywheel drift monitor's
        food, not ours — accepted and ignored here."""
        if self.state != "canary":
            return
        with self._lock:
            obs = self._obs
            if not obs:
                return
            key = "candidate" if generation == "candidate" else "live"
            obs[f"{key}_lat"].extend(latencies_s)
            obs[f"{key}_disp"].append(dispatch_s)
            if error is not None:
                obs[f"{key}_err"] += len(latencies_s)

    # -- shadow eval -------------------------------------------------------

    def _eval_shard(self) -> Tuple[np.ndarray, tuple]:
        if self._eval_batch is None:
            self._eval_batch = pinned_eval_shard(
                self.cfg, self.sm.engine, examples=self._eval_examples)
        return self._eval_batch

    def _score(self, generation: Optional[str]) -> float:
        """The family's watched metric for one generation over the pinned
        shard, computed from the engine's SERVING outputs (top-1 from
        logits, mIoU from class-id masks, box-count agreement from decoded
        detections / CenterNet peaks, PCK from pose heatmaps —
        core/scoring.score_serving_outputs), scored on the exact payloads
        clients get. Runs at the model's ACTIVE precision: when the quant
        gate flipped serving to int8, candidates are shadow-scored at int8
        too — the gate compares what clients would actually receive."""
        images, targets = self._eval_shard()
        out = self.sm.engine.predict(images, generation=generation)
        return scoring.score_serving_outputs(self.cfg, out, targets)

    # -- the pipeline ------------------------------------------------------

    def propose(self, epoch: int, variables, provenance: Optional[dict]
                ) -> str:
        """Run the full shadow -> gate -> canary -> promote/rollback
        pipeline for one verified candidate. Returns the decision constant;
        the caller (serve/reload.py) caches every refusal/rollback so the
        epoch is never re-evaluated, and counts the outcome on /healthz."""
        t0 = time.monotonic()
        if self._abort.is_set():
            return ROLLED_BACK_ABORT  # draining: don't start a pipeline
        engine = self.sm.engine
        fault_kind = self.faults.promote_regression(epoch)
        # -- stage (signature check: anything else needs a new engine) -----
        try:
            engine.stage_candidate(
                variables, provenance,
                inject_delay_s=(FAULT_LATENCY_SPIKE_S
                                if fault_kind == "latency" else 0.0))
        except ValueError as e:
            return self._decide(REFUSED_INCOMPATIBLE, epoch, t0,
                                detail=str(e))
        try:
            # -- shadow: score BOTH generations on the pinned shard --------
            self.state = "shadow"
            self.shadow_evals += 1
            metric_live = self._score(None)
            metric_cand = self._score("candidate")
            if fault_kind == "accuracy":
                metric_cand = max(0.0, metric_cand - FAULT_ACCURACY_DROP)
            delta = metric_cand - metric_live
            extra = {"metric_live": round(metric_live, 4),
                     "metric_candidate": round(metric_cand, 4),
                     "metric_delta": round(delta, 4),
                     "watch": scoring.watch_metric_name(self.cfg)}
            if delta < self.gate_min_delta:
                engine.drop_candidate()
                return self._decide(
                    REFUSED_GATE, epoch, t0, extra=extra,
                    detail=f"shadow {extra['watch']} delta {delta:+.4f} "
                           f"below gate {self.gate_min_delta:+.4f}")
            # -- canary: route a fraction of live traffic for the window ---
            with self._lock:
                self._obs = {"live_lat": [], "candidate_lat": [],
                             "live_disp": [], "candidate_disp": [],
                             "live_err": 0, "candidate_err": 0}
                self._route_acc = 0.0
                self.state = "canary"
            deadline = time.monotonic() + self.canary_window_s
            while time.monotonic() < deadline:
                if self._abort.wait(min(0.025, self.canary_window_s or 0.025)):
                    break
            with self._lock:
                self.state = "shadow"   # stop routing before deciding
                obs, self._obs = self._obs, {}
            extra.update(self._canary_summary(obs))
            if self._abort.is_set():
                engine.drop_candidate()
                return self._decide(ROLLED_BACK_ABORT, epoch, t0, extra=extra,
                                    detail="shutdown mid-canary: retreated "
                                           "to the incumbent before drain")
            bad = self._canary_regressed(obs)
            if bad:
                engine.drop_candidate()
                return self._decide(ROLLED_BACK_CANARY, epoch, t0,
                                    extra=extra, detail=bad)
            # -- promote: one reference assignment, fleet-wide -------------
            engine.promote_candidate()
            return self._decide(PROMOTED, epoch, t0, extra=extra)
        except BaseException:
            # a failed pipeline must never leave a half-staged candidate
            engine.drop_candidate()
            self.state = "idle"
            raise

    def _canary_summary(self, obs: dict) -> dict:
        out = {"canary_requests": len(obs["candidate_lat"]),
               "baseline_requests": len(obs["live_lat"]),
               "canary_errors": obs["candidate_err"],
               "baseline_errors": obs["live_err"]}
        for key in ("live", "candidate"):
            lat = obs[f"{key}_lat"]
            if lat:
                out[f"{key}_p99_ms"] = round(float(np.percentile(
                    np.asarray(lat, np.float64), 99)) * 1000.0, 3)
            disp = obs[f"{key}_disp"]
            if disp:
                out[f"{key}_dispatch_p50_ms"] = round(float(np.median(
                    np.asarray(disp, np.float64))) * 1000.0, 3)
        return out

    def _canary_regressed(self, obs: dict) -> Optional[str]:
        """The rollback trigger: canary error rate above baseline by more
        than `error_rate_delta`, or candidate dispatch time above
        `p99_factor` x the live generation's. The latency comparison runs
        on per-batch DEVICE DISPATCH time, not request latency: the single
        dispatcher serializes batches, so a slow candidate batch inflates
        the queue wait of every live request behind it (head-of-line
        blocking) and request-level p99s converge — dispatch time is the
        component a generation wholly owns. Needs `canary_min_requests`
        canary samples (tiny samples make noisy quantiles); a window with
        no canary traffic at all decides on the shadow gate alone — no
        live evidence is not negative evidence."""
        n_cand = len(obs["candidate_lat"]) + obs["candidate_err"]
        n_live = len(obs["live_lat"]) + obs["live_err"]
        if n_cand == 0:
            return None
        err_cand = obs["candidate_err"] / n_cand
        err_live = (obs["live_err"] / n_live) if n_live else 0.0
        if err_cand > err_live + self.error_rate_delta:
            return (f"canary error rate {err_cand:.3f} vs baseline "
                    f"{err_live:.3f} (allowed +{self.error_rate_delta})")
        if (len(obs["candidate_lat"]) >= self.canary_min_requests
                and obs["live_disp"] and obs["candidate_disp"]):
            disp_c = float(np.median(
                np.asarray(obs["candidate_disp"], np.float64)))
            disp_l = float(np.median(
                np.asarray(obs["live_disp"], np.float64)))
            if disp_c > self.p99_factor * disp_l:
                return (f"canary dispatch {disp_c * 1000:.1f}ms vs "
                        f"baseline {disp_l * 1000:.1f}ms per batch "
                        f"(allowed {self.p99_factor:g}x)")
        return None

    # -- bookkeeping -------------------------------------------------------

    def _decide(self, decision: str, epoch: int, t0: float, *,
                extra: Optional[dict] = None, detail: str = "") -> str:
        record = {"decision": decision, "epoch": int(epoch),
                  "secs": round(time.monotonic() - t0, 3),
                  "unix": time.time(), **(extra or {})}
        if detail:
            record["detail"] = detail
        flywheel_id = self.flywheel_id
        if flywheel_id is not None:
            record["flywheel_id"] = flywheel_id
        with self._lock:
            self.state = "idle"
            self.history.append(record)
            del self.history[:-self._history_limit]
            self._events += 1
            step = self._events
        metrics = {f"promote_{decision}": 1.0, "promote_epoch": float(epoch)}
        for k in ("metric_delta", "canary_requests"):
            if extra and k in extra:
                metrics[f"promote_{k}"] = float(extra[k])
        log_resilience_event(self.logger, step, metrics,
                             flywheel_id=flywheel_id)
        # stderr like the reload layer: a promotion decision must be loud
        # on the replica that took it, not only in the metrics stream
        print(f"[serve-promote:{self.sm.name}] epoch {epoch}: {decision} "
              f"in {record['secs']:.2f}s"
              + (f" ({detail})" if detail else ""),
              file=sys.stderr, flush=True)
        return decision

    def abort(self) -> None:
        """Interrupt any in-flight pipeline (drain/SIGTERM path): an active
        canary rolls back to the incumbent promptly; later proposals are
        refused until the flag is cleared. Idempotent."""
        self._abort.set()

    def describe(self) -> dict:
        """The /healthz promotion record: live state, knobs, and the
        decision history (newest last)."""
        with self._lock:
            return {
                "state": self.state,
                "gate_min_delta": self.gate_min_delta,
                "canary_frac": self.canary_frac,
                "canary_window_s": self.canary_window_s,
                "decisions": [dict(r) for r in self.history],
            }


def attach_promoters(fleet, *, gate_min_delta: float,
                     canary_frac: float, canary_window_s: float,
                     logger=None,
                     warn: Callable[[str], None] = None) -> int:
    """Attach a PromotionController to every workdir-backed, gatable model
    in the fleet (the serve CLI's `--promote-gate` wiring). Non-gatable
    families and static-weight models are skipped with a warning — they
    keep the plain integrity-verified reload path. Returns how many models
    got a controller."""
    n = 0
    for sm in fleet:
        if not sm.workdir:
            continue
        try:
            PromotionController(
                sm, gate_min_delta=gate_min_delta, canary_frac=canary_frac,
                canary_window_s=canary_window_s, logger=logger)
            n += 1
        except ValueError as e:
            if warn is not None:
                warn(f"[serve:{sm.name}] promotion gate skipped: {e}")
    return n
