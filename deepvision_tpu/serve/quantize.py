"""Calibrated int8 serving with a hard accuracy gate.

The r05 profile pinned ResNet-50 serving at 97.4% of the HBM roof — on a
bandwidth-bound model the lever is bytes, and int8 weights are 4x smaller
than the f32 tree the bf16 buckets dispatch with. This module is the
serve-side sequel to the bf16 BN/residual cut: post-training quantization
(ops/quant.py — per-channel weight scales, per-tensor activation scales
from one calibration pass, int8 conv/dense with f32 heads and
dequant-at-boundaries), compiled as int8 bucket variants BESIDE the bf16
buckets in the engine's AOT cache, behind a **hard accuracy-delta gate**:

1. **Calibrate.** Replay the family's pinned deterministic shard
   (core/scoring.pinned_shard — the same shard recipe promotion's shadow
   eval uses) through the f32 predict jaxpr, recording per-equation
   activation ranges. One pass, pinned per (config, seed) down to the byte.
2. **Compile.** Per bucket, re-trace the predict at that batch size, plan
   the identical equation set (asserted), and AOT-compile the int8 twin —
   a one-time cost at arm time; no request ever traces.
3. **Gate.** Score the bf16 path and the int8 path on the pinned shard
   with the family's watched metric (top-1 / mIoU / box-count / PCK —
   core/scoring.score_serving_outputs, the same scoring promotion gates
   on). int8 goes live ONLY if `score_int8 - score_bf16 >= -gate`; a
   regression beyond the gate refuses loudly — the engine keeps serving
   bf16, the decision lands on stderr, the `resilience_` stream
   (`resilience_quant_refused`) and /healthz.

`DEEPVISION_FAULT_QUANT_REGRESS=1` (utils/faults.py) deterministically
degrades the int8 score so the refusal path is provable end-to-end —
preflight's `quant` check arms it and asserts the fallback.

Weight generations stay first-class at int8: the quantizer's activation
scales are pinned once, weight scales are data-free, so hot reload and
promotion re-quantize a new checkpoint under the SAME compiled programs
(`PredictEngine.swap_variables` / `stage_candidate` call back into
`Quantizer.quantize` — zero recompiles, signature-checked).
"""

from __future__ import annotations

import sys
import time
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import scoring
from ..core.resilience import log_resilience_event
from ..ops import quant
from ..utils.faults import FaultInjector

# default hard gate: int8 may cost at most 2 points of the watched metric
DEFAULT_GATE = 0.02
DEFAULT_CALIB_EXAMPLES = 64

# the armed DEEPVISION_FAULT_QUANT_REGRESS injector subtracts this from the
# int8 score — large against any sane gate, deterministic regardless of how
# the (possibly random-weight) model actually scores
FAULT_SCORE_DROP = 0.5

QUANT_ENABLED = "int8_enabled"
QUANT_REFUSED = "refused_regression"
# the PLAN itself refused (ops/quant.QuantRefusal — e.g. a transformer whose
# projections cannot quantize): no int8 twin exists at all, the engine keeps
# serving bf16, and the named reason lands on /healthz
QUANT_REFUSED_PLAN = "refused_plan"


class Quantizer:
    """One engine's quantization state: the pinned activation scales plus
    everything needed to (re-)quantize any signature-equal weight
    generation and to build the int8 twin of any bucket's predict.

    Built once at arm time from the f32 predict and ONE calibration batch;
    after that, `quantize(variables)` is the only per-generation work
    (data-free weight scales), which is what keeps hot reload and promotion
    recompile-free at int8."""

    def __init__(self, predict_fn: Callable, variables, calib_images,
                 head_dims=frozenset()):
        self._predict_fn = predict_fn
        self.head_dims = frozenset(head_dims)
        closed = jax.make_jaxpr(predict_fn)(variables, calib_images)
        plan = quant.plan_quantization(closed, self.head_dims)
        if not plan.eqns:
            raise ValueError(
                "nothing to quantize: no conv/dense with a weight operand "
                "outside the f32 heads — int8 serving would be a no-op")
        quant.calibrate(plan, closed, variables, calib_images)
        self._calib_plan = plan
        # activation scales in PLANNED ORDER: bucket re-traces bake them by
        # position (equation indices shift with batch-size-dependent
        # canonicalization; the planned op sequence does not)
        self._scales: List[float] = [plan.act_scales[q.eqn_index]
                                     for q in plan.eqns]
        self._prims = [q.prim for q in plan.eqns]
        self._leaf_indices = plan.leaf_indices

    def summary(self) -> dict:
        return self._calib_plan.summary()

    def _plan_for(self, variables, images_spec) -> tuple:
        """(calibrated plan, closed jaxpr) for one bucket's batch size —
        the re-trace must plan the same op sequence as calibration, or the
        positional scale assignment would be wrong (asserted, not hoped)."""
        closed = jax.make_jaxpr(self._predict_fn)(variables, images_spec)
        plan = quant.plan_quantization(closed, self.head_dims)
        if [q.prim for q in plan.eqns] != self._prims \
                or plan.leaf_indices != self._leaf_indices:
            raise ValueError(
                f"bucket re-trace planned a different equation set "
                f"({len(plan.eqns)} vs {len(self._prims)} at calibration) — "
                f"the predict is not batch-polymorphic; cannot quantize")
        plan.act_scales = {q.eqn_index: s
                           for q, s in zip(plan.eqns, self._scales)}
        return plan, closed

    def quantized_fn(self, variables, images_spec) -> Callable:
        """The int8 predict twin for one bucket: `(qvariables, images) ->
        outputs`, same output pytree as the f32 predict."""
        plan, closed = self._plan_for(variables, images_spec)
        out_tree = jax.tree_util.tree_structure(
            jax.eval_shape(self._predict_fn, variables, images_spec))
        return quant.quantized_predict_fn(plan, closed, out_tree)

    def quantize(self, variables):
        """int8-quantize one weight generation under the pinned plan:
        per-channel weight scales recomputed from these weights (data-free),
        activation scales unchanged — the compiled programs run the result
        as-is."""
        return quant.quantize_variables(self._calib_plan, variables)


def arm_int8(engine, cfg=None, *,
             gate: float = DEFAULT_GATE,
             examples: int = DEFAULT_CALIB_EXAMPLES,
             seed: int = scoring.DEFAULT_SHARD_SEED,
             shard=None,
             logger=None,
             faults: Optional[FaultInjector] = None,
             verbose: bool = True) -> dict:
    """Calibrate, compile, and GATE int8 serving for one engine.

    On a gate pass the engine's active precision flips to int8 (bf16
    buckets stay compiled — per-request `precision` overrides keep
    working); on a regression beyond `gate` the engine is left exactly as
    it was, serving bf16, with the refusal logged to stderr and the
    `resilience_` stream. Returns the decision record (also stored as
    `engine.quant_decision` and reported on /healthz)."""
    from ..configs import get_config
    cfg = cfg or get_config(engine.name)
    if cfg.family not in scoring.GATED_FAMILIES:
        raise ValueError(
            f"config {cfg.name!r} (family {cfg.family!r}) has no "
            f"predict-side watch metric to gate int8 against — gated "
            f"families: {scoring.GATED_FAMILIES}")
    faults = faults if faults is not None else FaultInjector.from_env()
    t0 = time.monotonic()
    images, targets = shard if shard is not None else scoring.pinned_shard(
        cfg, image_size=engine.example_shape[0],
        input_dtype=engine.input_dtype, examples=examples, seed=seed)
    watch = scoring.watch_metric_name(cfg)

    # calibrate + compile the int8 bucket twins (one-time arm cost)
    try:
        quantizer = Quantizer(engine._predict_fn, engine._variables,
                              jnp.asarray(images),
                              head_dims=scoring.serving_head_dims(cfg))
    except quant.QuantRefusal as exc:
        # the plan refused by name (never silently serve a half-quantized
        # transformer): loud record on stderr, the resilience stream, and
        # /healthz — the engine is untouched, still serving bf16
        decision = {
            "decision": QUANT_REFUSED_PLAN,
            "reason": exc.reason,
            "detail": str(exc),
            "watch": watch,
            "secs": round(time.monotonic() - t0, 3),
            "unix": time.time(),
        }
        engine.quant_decision = decision
        log_resilience_event(logger, 1, {"quant_refused": 1.0})
        print(f"[serve-quant:{engine.name}] {QUANT_REFUSED_PLAN} "
              f"({exc.reason}): {exc} — serving bf16",
              file=sys.stderr, flush=True)
        return decision
    engine.enable_int8(quantizer, verbose=verbose)

    # the hard gate: identical pinned inputs, two precisions
    metric_bf16 = scoring.score_serving_outputs(
        cfg, engine.predict(images, precision="bf16"), targets)
    metric_int8 = scoring.score_serving_outputs(
        cfg, engine.predict(images, precision="int8"), targets)
    if faults.quant_regression():
        metric_int8 = max(0.0, metric_int8 - FAULT_SCORE_DROP)
    delta = metric_int8 - metric_bf16
    passed = delta >= -abs(gate)
    decision = {
        "decision": QUANT_ENABLED if passed else QUANT_REFUSED,
        "watch": watch,
        "metric_bf16": round(metric_bf16, 4),
        "metric_int8": round(metric_int8, 4),
        "delta": round(delta, 4),
        "gate": abs(gate),
        "calibration_examples": int(np.shape(images)[0]),
        "quantized_eqns": quantizer.summary()["quantized"],
        # the full plan split — in particular `skipped_attention`, the
        # float softmax-adjacent contractions a ViT deliberately keeps
        # (named on /healthz; never a silent half-quantization)
        "plan": quantizer.summary(),
        "weight_bytes_bf16": quant.tree_nbytes(engine._variables),
        "weight_bytes_int8": quant.tree_nbytes(engine._qvariables),
        "secs": round(time.monotonic() - t0, 3),
        "unix": time.time(),
    }
    if passed:
        engine.set_precision("int8")
        log_resilience_event(logger, 1, {
            "quant_enabled": 1.0, "quant_delta": float(delta)})
    else:
        engine.disable_int8()
        log_resilience_event(logger, 1, {
            "quant_refused": 1.0, "quant_delta": float(delta)})
    engine.quant_decision = decision
    print(f"[serve-quant:{engine.name}] {decision['decision']}: "
          f"{watch} bf16 {metric_bf16:.4f} vs int8 {metric_int8:.4f} "
          f"(delta {delta:+.4f}, gate -{abs(gate):g}) — "
          + (f"int8 live, weights "
             f"{decision['weight_bytes_bf16'] / 1e6:.1f}MB -> "
             f"{decision['weight_bytes_int8'] / 1e6:.1f}MB"
             if passed else "REFUSED, serving bf16"),
          file=sys.stderr, flush=True)
    return decision
