"""Hot weight reload: new verified checkpoints swap in with zero downtime.

The fleet serves weights frozen at startup; training keeps committing new
epochs into the same run dirs. This module closes the loop without a
restart (and without the compile stall a restart pays): a background
poller watches each served model's `<workdir>/ckpt` for committed epochs
newer than the weights currently live, and for each candidate:

1. **Verify first, cheaply.** `core/integrity.verify_epoch` checks the
   PR 4 manifest at the file level — no deserialization. A CORRUPT
   candidate is refused permanently (logged loudly, counted on /healthz,
   written to the `resilience_` metrics stream) and the old weights keep
   serving; a MISSING_MANIFEST candidate is simply not ready yet (the
   manifest commits strictly AFTER the Orbax commit), so the poller waits.
2. **Deserialize off the request path.** The candidate restores through
   the config's own trainer family with STRICT integrity verification
   (`engine.load_checkpoint_weights` — the exact code path startup uses,
   including the deep per-leaf hash check and EMA-weights-win), entirely
   on the poller thread. Request threads never block on I/O or hashing.
   This is the mesh-aware restore (core/reshard.py): a checkpoint the
   training pod saved on N chips hot-reloads on this host's device count
   with no manual surgery, and the swap provenance records `resharded`.
3. **Swap atomically.** `PredictEngine.swap_variables` stages the new
   weights on device, checks them against the compiled signature (same
   tree/shapes/dtypes — so the AOT bucket cache is reused and NOTHING
   recompiles), and flips one reference. In-flight batches complete
   against the old weights; the next dispatch serves the new epoch.
   /healthz provenance (epoch, manifest hash, verified) advances in the
   same step.

A candidate whose shapes changed (someone retrained a different
architecture into the same run dir) is refused as incompatible — that
deployment needs a new engine process, not a swap.

When a served model carries a PromotionController (serve/promote.py —
the `--promote-gate` deployment), step 3 is delegated: instead of flipping
directly, the verified candidate runs the shadow-eval gate and canary
window, and the reloader records the verdict — a refused or rolled-back
epoch joins the same permanent refusal cache as a corrupt one, so a bad
epoch is hashed, restored, and scored exactly once.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, Iterable, Optional, Set

from ..core import integrity
from ..core.checkpoint import CheckpointCorruptionError
from ..core.resilience import log_resilience_event
from . import promote
from .engine import load_checkpoint_weights
from .fleet import ServedModel


def _log(name: str, msg: str) -> None:
    # stderr like the checkpoint layer: reload outcomes must be loud on the
    # replica that took them, not only in the metrics stream
    print(f"[serve-reload:{name}] {msg}", file=sys.stderr, flush=True)


class WeightReloader:
    """Background poller over the fleet's workdir-backed models.

    `start()` spawns the daemon thread (`poll_every_s` cadence);
    `check_once()` runs one full sweep synchronously — the unit tests' and
    preflight's handle, and exactly what the thread calls. `stop()` joins.
    One reloader serves the whole fleet: candidate restores are serialized
    on the poller thread by construction, so two models' reloads never
    hash/deserialize concurrently with each other (they do run concurrently
    with request traffic — that is the point)."""

    def __init__(self, models: Iterable[ServedModel], *,
                 poll_every_s: float = 10.0,
                 logger=None, verify: bool = True):
        self.models = [sm for sm in models if sm.workdir]
        self.poll_every_s = float(poll_every_s)
        self.logger = logger        # MetricsLogger for the resilience_ stream
        self.verify = verify
        # per-model epochs permanently refused (corrupt / incompatible):
        # re-verifying a known-bad candidate every poll would hash the same
        # bad bytes forever
        self._refused: Dict[str, Set[int]] = {sm.name: set()
                                              for sm in self.models}
        self._waiting_logged: Dict[str, Set[int]] = {sm.name: set()
                                                     for sm in self.models}
        self._events = 0            # step counter for the metrics stream
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "WeightReloader":
        if self._thread is None and self.models and self.poll_every_s > 0:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="weight-reloader")
            self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_every_s):
            try:
                self.check_once()
            except Exception as e:  # noqa: BLE001 — the poller must survive
                # transient filesystem weirdness; the next tick retries
                _log("fleet", f"poll failed (will retry): {e!r}")

    # -- one sweep ---------------------------------------------------------

    def check_once(self) -> int:
        """Sweep every watched model once; returns how many swaps landed."""
        swapped = 0
        for sm in self.models:
            if self._check_model(sm):
                swapped += 1
        return swapped

    def _current_epoch(self, sm: ServedModel) -> int:
        got = sm.engine.provenance.get("checkpoint_epoch")
        return -1 if got is None else int(got)  # random-init: anything wins

    def _check_model(self, sm: ServedModel) -> bool:
        ckpt_dir = os.path.join(sm.workdir, "ckpt")
        current = self._current_epoch(sm)
        refused = self._refused[sm.name]
        candidates = [e for e in integrity.committed_epochs(ckpt_dir)
                      if e > current and e not in refused]
        if not candidates:
            return False
        epoch = max(candidates)   # newest first; older ones are stale news
        status, detail, _ = integrity.verify_epoch(ckpt_dir, epoch)
        if status == integrity.MISSING_MANIFEST:
            # the finalizer commits the manifest AFTER the Orbax commit:
            # mid-save, not corruption — wait for the next poll (log once)
            if epoch not in self._waiting_logged[sm.name]:
                self._waiting_logged[sm.name].add(epoch)
                _log(sm.name, f"epoch {epoch} committed but not yet "
                              f"manifested — waiting for the save to "
                              f"finalize")
            return False
        if status != integrity.OK:
            self._refuse(sm, epoch, "refused_corrupt",
                         f"candidate epoch {epoch} failed integrity "
                         f"verification ({detail}) — NOT swapped; old "
                         f"weights keep serving. Audit with `python -m "
                         f"deepvision_tpu fsck {ckpt_dir}`")
            return False
        # file-verified: deserialize + deep-verify off the request path
        try:
            _, variables, provenance, _ = load_checkpoint_weights(
                sm.name, sm.workdir, checkpoint=epoch, verify=self.verify,
                verbose=False)
        except (CheckpointCorruptionError, FileNotFoundError, OSError,
                ValueError) as e:
            self._refuse(sm, epoch, "refused_corrupt",
                         f"candidate epoch {epoch} failed strict restore "
                         f"({e}) — NOT swapped; old weights keep serving")
            return False
        promoter = sm.promoter
        if promoter is not None:
            # accuracy-gated promotion (serve/promote.py): the controller
            # runs shadow eval, the metric-delta gate, and the canary
            # window, and flips or retreats itself — the reloader's job
            # reduces to caching the verdict so a refused/rolled-back
            # epoch is never re-evaluated, and counting it on /healthz.
            decision = promoter.propose(epoch, variables, provenance)
            if decision == promote.ROLLED_BACK_ABORT:
                return False   # shutting down: not the epoch's fault —
                               # don't cache, a restart may re-evaluate
            if decision != promote.PROMOTED:
                counter = {promote.REFUSED_GATE: "refused_gate",
                           promote.ROLLED_BACK_CANARY: "rolled_back"}.get(
                    decision, "refused_incompatible")
                record = (promoter.history[-1] if promoter.history else {})
                detail = record.get("detail",
                                    "see /healthz promotion history")
                incumbent = current if current >= 0 else "random-init"
                self._refuse(sm, epoch, counter,
                             f"candidate epoch {epoch} {decision} "
                             f"({detail}) — incumbent epoch {incumbent} "
                             f"keeps serving")
                return False
        else:
            try:
                sm.engine.swap_variables(variables, provenance=provenance)
            except ValueError as e:
                self._refuse(sm, epoch, "refused_incompatible", str(e))
                return False
        with sm.reload_lock:
            sm.reload_stats["reloads"] += 1
            sm.reload_stats["last_reload_epoch"] = float(epoch)
            sm.reload_stats["last_reload_unix"] = time.time()
        self._event({"reload_swapped": 1.0, "reload_epoch": float(epoch)})
        _log(sm.name, f"hot-swapped weights: epoch {current if current >= 0 else 'random-init'} "
                      f"-> {epoch} (manifest "
                      f"{(provenance.get('manifest_sha256') or '')[:12]}, "
                      f"verified={provenance.get('verified')}"
                      + (", resharded from the saved mesh to this host"
                         if provenance.get("resharded") else "")
                      + (", promoted through the shadow/canary gate"
                         if promoter is not None else "")
                      + (", re-quantized under the pinned int8 scales"
                         if getattr(sm.engine, "int8_enabled", False)
                         else "")
                      + "; AOT bucket cache reused, zero recompiles)")
        return True

    def _refuse(self, sm: ServedModel, epoch: int, counter: str,
                msg: str) -> None:
        self._refused[sm.name].add(epoch)
        with sm.reload_lock:
            sm.reload_stats[counter] += 1
        self._event({f"reload_{counter}": 1.0,
                     "reload_refused_epoch": float(epoch)})
        _log(sm.name, msg)

    def _event(self, metrics: dict) -> None:
        self._events += 1
        log_resilience_event(self.logger, self._events, metrics)
