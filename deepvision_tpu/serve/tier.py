"""Replica tier: one router process, N supervised replica processes.

Every serving PR so far hardens ONE process — batcher backpressure,
circuit breaker, admission control, graceful drain. One process is still
one blast radius: a segfault, a wedged dispatcher, or a bad promotion
takes the whole front door with it. The tier splits the front door from
the model processes:

    client -> TierRouter (this module, stdlib HTTP) -> replica 0..N-1
              least-loaded routing                     (serve/replica.py,
              per-replica circuit breaking              each the full
              supervised restart + backoff              fleet server)
              rolling promotion
              merged /metrics

Contracts the router keeps (rehearsable via utils/faults.py and pinned
by tests/test_tier.py + preflight check #18):

- **Least-loaded routing**: each request goes to the admitted replica
  with the lowest `inflight + queue_depth/workers` score (inflight is the
  router's own immediate signal; queue depth/workers come from the
  replica's `/healthz`, polled every `health_every_s`). Ties rotate.
- **Ejection on the spot**: a connection-refused (crashed replica) ejects
  the slot immediately — no K-failure wait, a dead socket is not a
  statistic. Wedges (accepts, never answers) are caught by the
  deadline-bounded health probe: `probe_timeout_s` bounds every probe, K
  consecutive failures open the slot's `CircuitBreaker`
  (serve/autoscale.py — same pattern, tier-level) and the slot stops
  taking traffic; half-open probes re-admit it when it answers again.
- **Supervised restart**: slots launched by the router (argv-bearing) are
  respawned after exit with exponential backoff
  (`restart_backoff_s`..`restart_backoff_max_s`, reset on readmission).
  Every transition is a `resilience_tier_*` event on the tier's JSONL
  stream: `tier_replica_exit`, `tier_replica_restarted`,
  `tier_replica_ejected`, `tier_replica_readmitted`, `tier_roll_*`.
- **Zero failed responses across a replica loss**: a transport failure or
  5xx on one replica retries the SAME request on the next admitted
  replica (the request was never dispatched — the batcher refuses before
  queueing on 503/429, and a crashed socket never dispatched). 400/404
  (and a clean 200) are authoritative and pass through verbatim —
  including the 404 served-models body.
- **Rolling promotion**: `POST /roll` (or `roll_every_s`) drives the
  PR 11 gate one replica at a time through each replica's synchronous
  `POST /reload`: promote -> advance to the next replica; any refusal
  (gate, canary rollback, corrupt, incompatible) STOPS the roll — a
  regressing candidate is exposed on exactly one replica. Responses
  carry `generation` + `weights_epoch` pinned at submit time by the
  replica (serve/fleet.py `submit_routed`), so mixed-generation audits
  are per-response facts.
- **Warm boot**: every replica shares one persistent XLA compile cache
  dir; only the tier's first boot compiles. `/healthz` aggregates each
  replica's compile hit/miss counts so "restart = warm" is checkable.
- **Merged /metrics**: one exposition for the whole tier — counters and
  gauges per replica (`replica` label), fixed-bucket histograms summed
  (obs/export.py `merge_expositions`), plus the router's own
  `deepvision_tier_*` families. Valid under `validate_prometheus_text`.

Router endpoints:

    POST /predict[/<model>]  forward with retry; adds X-Tier-Replica
    GET  /healthz            tier status + per-replica records + roll state
    GET  /metrics            merged exposition (replica label + tier families)
    GET  /stats              router counters (routed, retries, ejections...)
    GET  /trace              router-side spans (chrome://tracing JSON)
    POST /roll               run one rolling promotion sweep now

`python -m deepvision_tpu.serve.tier -m lenet5 --replicas 3` boots the
tier; `--smoke` runs synthetic HTTP load through the router (with
`--kill-one`, SIGKILLs a replica mid-load and requires zero failed
responses plus a supervised readmission) and prints one JSON verdict.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence

from ..core.metrics import MetricsLogger
from ..core.resilience import GracefulShutdown, log_resilience_event
from ..obs.export import _emit, chrome_trace, merge_expositions
from ..obs.trace import Tracer, new_request_id
from .autoscale import CLOSED, CircuitBreaker

_RELOAD_KEYS = ("reloads", "refused_gate", "rolled_back", "refused_corrupt",
                "refused_incompatible")


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind 0, read, close). The replica
    HTTPServer sets allow_reuse_address, so a respawned replica rebinds
    the same slot port even straight after a crash."""
    s = socket.socket()
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _http_json(url: str, *, timeout: float, method: str = "GET",
               body: Optional[bytes] = None,
               headers: Optional[dict] = None):
    """(status, parsed-json) for small control-plane calls. Raises on
    transport failure; HTTP errors return their status + body."""
    req = urllib.request.Request(url, data=body, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode() or "{}")
        except Exception:  # noqa: BLE001 — body shape is the replica's call
            return e.code, {}


class ReplicaHandle:
    """One tier slot: the replica URL, the (optional) argv the supervisor
    respawns it with, its circuit breaker, and the router-side load/health
    signals routing reads. Mutable fields are guarded by `lock` (health
    poller, supervisor, and request threads all touch them)."""

    def __init__(self, rid: str, url: str, *,
                 argv: Optional[Sequence[str]] = None,
                 env: Optional[dict] = None,
                 slot: int = 0,
                 breaker_k: int = 3,
                 breaker_cooldown_s: float = 1.0):
        self.rid = str(rid)
        self.slot = int(slot)
        self.url = url.rstrip("/")
        self.argv = list(argv) if argv else None
        self.env = dict(env) if env else None
        self.breaker_k = int(breaker_k)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.breaker = self._fresh_breaker()
        self.lock = threading.Lock()
        self.proc: Optional[subprocess.Popen] = None
        # a supervised slot is dead until its process boots AND answers
        # /healthz; an attach-only slot (tests) is unknown until probed
        self.dead = self.argv is not None
        self.healthy = False
        self.draining = False
        self.queue_depth = 0
        self.workers = 1
        self.inflight = 0
        self.routed = 0          # requests answered through this slot
        self.failures = 0        # transport/5xx outcomes charged to it
        self.launches = 0
        self.exits = 0
        self.last_exit_code: Optional[int] = None
        self.last_health: Optional[dict] = None
        self.last_health_unix = 0.0
        # supervisor state: launch immediately on start, back off on exit
        self.pending_restart = self.argv is not None
        self.next_restart_at = 0.0
        self.backoff_s = 0.5
        self.routable_prev = False

    def _fresh_breaker(self) -> CircuitBreaker:
        # recreated on every respawn: a new process owes nothing to the
        # failure streak that killed its predecessor
        return CircuitBreaker(f"tier-replica-{self.rid}",
                              k=self.breaker_k,
                              cooldown_s=self.breaker_cooldown_s)

    @property
    def routable(self) -> bool:
        return (not self.dead and self.healthy and not self.draining
                and self.breaker.state == CLOSED)

    def score(self) -> float:
        """Least-loaded routing score: the router's own in-flight count
        plus the replica's queue depth normalised by its dispatcher pool
        (8 queued on 4 workers loads like 2 queued on 1)."""
        with self.lock:
            return self.inflight + self.queue_depth / max(1, self.workers)

    def describe(self) -> dict:
        with self.lock:
            lh = self.last_health or {}
            models = lh.get("models") or {}
            d = {
                "replica": self.rid, "slot": self.slot, "url": self.url,
                "routable": self.routable, "healthy": self.healthy,
                "draining": self.draining, "dead": self.dead,
                "supervised": self.argv is not None,
                "inflight": self.inflight, "queue_depth": self.queue_depth,
                "workers": self.workers, "routed": self.routed,
                "failures": self.failures, "launches": self.launches,
                "restarts": max(0, self.launches - 1), "exits": self.exits,
                "last_exit_code": self.last_exit_code,
                "breaker": self.breaker.describe(),
                "weights_epoch": (lh.get("weights") or {}).get(
                    "checkpoint_epoch"),
                # warm-boot audit: cache_misses == 0 on every boot after
                # the first means the shared compile cache is doing its job
                "compile": {name: (m.get("compile") or {})
                            for name, m in models.items()},
                # the replica autoscaler's across-mesh escalation: within-
                # mesh workers are exhausted and the model still sheds —
                # the tier (this layer) owns the next lever, a new replica
                "wants_scale_out": any(
                    (m.get("autoscale") or {}).get("wants_scale_out")
                    for m in models.values()),
                "mesh": {name: m.get("mesh")
                         for name, m in models.items()},
            }
        return d


class RollingPromotion:
    """Drives the PR 11 promotion gate across the tier one replica at a
    time. Each step is the replica's own synchronous `POST /reload`
    (serve/reload.py `check_once` — shadow eval, gate, optional canary all
    resolve before the response returns), so the router knows the verdict
    the moment the call completes:

        promoted      -> advance to the next replica
        any refusal   -> STOP: the candidate was exposed on exactly one
                         replica; the rest keep their generation
        no candidate  -> advance (nothing to do on that replica)
        unreachable   -> abort the sweep (supervisor will deal with it)
    """

    def __init__(self, router: "TierRouter", *, model: Optional[str] = None,
                 timeout_s: float = 600.0, ready_timeout_s: float = 30.0):
        self.router = router
        self.model = model
        self.timeout_s = float(timeout_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self._busy = threading.Lock()
        self._state_lock = threading.Lock()
        self.rolls = 0
        self.current: dict = {"state": "idle", "outcomes": []}
        self.history: List[dict] = []

    def describe(self) -> dict:
        with self._state_lock:
            return {"rolls": self.rolls, **self.current}

    def roll_once(self) -> dict:
        if not self._busy.acquire(blocking=False):
            return {"state": "busy",
                    "error": "a rolling promotion is already in progress"}
        try:
            return self._roll()
        finally:
            self._busy.release()

    def _wait_routable(self, h: ReplicaHandle) -> bool:
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            if h.routable:
                return True
            if self.router.stopped.wait(0.1):
                return False
        return h.routable

    def _reload_stats(self, h: ReplicaHandle) -> Dict[str, float]:
        _, js = _http_json(h.url + "/healthz",
                           timeout=self.router.probe_timeout_s)
        models = js.get("models") or {}
        mkey = self.model or (next(iter(models)) if models else None)
        rl = (models.get(mkey) or {}).get("reload") or {}
        return {k: float(rl.get(k, 0)) for k in _RELOAD_KEYS}

    def _roll(self) -> dict:
        router = self.router
        self._set({"state": "rolling", "outcomes": []})
        router._event({"tier_roll_started": 1.0})
        outcomes: List[dict] = []
        state, promoted = "idle", 0
        for h in router.replicas:
            if not self._wait_routable(h):
                outcomes.append({"replica": h.rid,
                                 "outcome": "skipped_unready"})
                self._set({"state": "rolling", "outcomes": list(outcomes)})
                continue
            try:
                before = self._reload_stats(h)
                code, js = _http_json(
                    h.url + "/reload", method="POST", body=b"{}",
                    headers={"Content-Type": "application/json"},
                    timeout=self.timeout_s)
            except Exception as e:  # noqa: BLE001 — sweep must report
                outcomes.append({"replica": h.rid, "outcome": "unreachable",
                                 "error": repr(e)})
                state = "aborted"
                router._event({"tier_roll_aborted": 1.0,
                               "replica_slot": float(h.slot)})
                break
            if code != 200:
                outcomes.append({"replica": h.rid, "outcome": "error",
                                 "status": code, "body": js})
                state = "aborted"
                router._event({"tier_roll_aborted": 1.0,
                               "replica_slot": float(h.slot)})
                break
            models = js.get("models") or {}
            mkey = self.model or (next(iter(models)) if models else None)
            rl = (models.get(mkey) or {}).get("reload") or {}
            delta = {k: float(rl.get(k, 0)) - before.get(k, 0.0)
                     for k in _RELOAD_KEYS}
            if int(js.get("swapped") or 0) > 0 or delta["reloads"] > 0:
                epoch = ((models.get(mkey) or {}).get("weights")
                         or {}).get("checkpoint_epoch")
                promoted += 1
                outcomes.append({"replica": h.rid, "outcome": "promoted",
                                 "epoch": epoch})
                router._event({"tier_roll_replica_promoted": 1.0,
                               "replica_slot": float(h.slot),
                               "epoch": float(epoch if epoch is not None
                                              else -1)})
                self._set({"state": "rolling", "outcomes": list(outcomes)})
                continue
            refusals = {k: v for k, v in delta.items()
                        if k != "reloads" and v > 0}
            if refusals:
                outcomes.append({"replica": h.rid, "outcome": "rolled_back",
                                 "refusals": refusals})
                state = "rolled_back"
                router._event({"tier_roll_rolled_back": 1.0,
                               "replica_slot": float(h.slot)})
                print(f"[tier] rolling promotion STOPPED at replica "
                      f"{h.rid}: candidate refused ({refusals}) — "
                      f"remaining replicas keep the live generation",
                      file=sys.stderr, flush=True)
                break
            outcomes.append({"replica": h.rid, "outcome": "no_candidate"})
        if state == "idle" and promoted:
            state = "promoted"
            router._event({"tier_roll_completed": 1.0,
                           "replicas_promoted": float(promoted)})
        rec = {"state": state, "outcomes": outcomes, "promoted": promoted}
        with self._state_lock:
            self.rolls += 1
            self.current = rec
            self.history.append(rec)
            del self.history[:-20]
        return rec

    def _set(self, rec: dict) -> None:
        with self._state_lock:
            self.current = rec


class TierRouter:
    """The tier front door + supervisor. Construct with ReplicaHandles
    (argv-bearing slots are launched and respawned; url-only slots are
    attached as-is — the test seam), `start()`, then direct traffic at
    `http://host:bound_port/predict`."""

    def __init__(self, replicas: Sequence[ReplicaHandle], *,
                 host: str = "127.0.0.1", port: int = 0,
                 health_every_s: float = 0.25,
                 probe_timeout_s: float = 1.0,
                 default_deadline_s: float = 30.0,
                 attempt_timeout_s: Optional[float] = None,
                 restart_backoff_s: float = 0.5,
                 restart_backoff_max_s: float = 8.0,
                 roll_model: Optional[str] = None,
                 roll_every_s: float = 0.0,
                 roll_timeout_s: float = 600.0,
                 log_dir: Optional[str] = None,
                 logger: Optional[MetricsLogger] = None,
                 tracer: Optional[Tracer] = None):
        if not replicas:
            raise ValueError("a tier needs at least one replica slot")
        self.replicas = list(replicas)
        self.host = host
        self._port = int(port)
        self.health_every_s = float(health_every_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.default_deadline_s = float(default_deadline_s)
        # per-attempt cap: without it a WEDGED replica (accepts, never
        # answers) burns the whole client deadline on attempt one and the
        # retry never happens. Applied only while OTHER untried replicas
        # remain — the last candidate always gets the full remainder.
        self.attempt_timeout_s = (float(attempt_timeout_s)
                                  if attempt_timeout_s else None)
        self.restart_backoff_s = float(restart_backoff_s)
        self.restart_backoff_max_s = float(restart_backoff_max_s)
        self.roll_every_s = float(roll_every_s)
        for h in self.replicas:
            h.backoff_s = self.restart_backoff_s
        self.logger = logger or (MetricsLogger(log_dir, name="tier")
                                 if log_dir else None)
        self.tracer = tracer if tracer is not None else Tracer()
        self.roll = RollingPromotion(self, model=roll_model,
                                     timeout_s=roll_timeout_s)
        self.stopped = threading.Event()
        self.ready = threading.Event()
        self.bound_port: Optional[int] = None
        self._stats_lock = threading.Lock()
        self.stats = {"requests": 0, "responses_2xx": 0, "responses_4xx": 0,
                      "responses_5xx": 0, "retries": 0, "no_replica": 0,
                      "ejections": 0, "readmissions": 0, "restarts": 0,
                      "exits": 0}
        self._event_seq = itertools.count(1)
        self._rr = itertools.count()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []

    # -- events / accounting -----------------------------------------------

    def _event(self, metrics: dict) -> None:
        log_resilience_event(self.logger, next(self._event_seq), metrics)

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] = self.stats.get(key, 0) + n

    def _note_routable(self, h: ReplicaHandle, cause: str = "") -> None:
        """Single source of ejection/readmission accounting: call after
        any state change; only transitions log/count."""
        now_routable = h.routable
        # transition detection under h.lock: supervisor, prober and router
        # threads all call this, and two observers of one transition must
        # not double-log it (or lose the backoff reset); logging stays
        # outside the lock
        with h.lock:
            if now_routable == h.routable_prev:
                return
            h.routable_prev = now_routable
            if now_routable:
                h.backoff_s = self.restart_backoff_s   # stable again
        if now_routable:
            self._bump("readmissions")
            self._event({"tier_replica_readmitted": 1.0,
                         "replica_slot": float(h.slot)})
            print(f"[tier] replica {h.rid} re-admitted "
                  f"(healthy, breaker {h.breaker.state})",
                  file=sys.stderr, flush=True)
        else:
            self._bump("ejections")
            self._event({"tier_replica_ejected": 1.0,
                         "replica_slot": float(h.slot)})
            print(f"[tier] replica {h.rid} ejected"
                  + (f" ({cause})" if cause else ""),
                  file=sys.stderr, flush=True)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        class _TierServer(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _TierServer((self.host, self._port), _Handler)
        self._httpd.router = self
        self.bound_port = self._httpd.server_address[1]
        for h in self.replicas:     # boot supervised slots immediately
            if h.argv is not None and h.proc is None:
                self._launch(h)
        self._threads = [
            threading.Thread(target=self._httpd.serve_forever,
                             name="tier-http", daemon=True),
            threading.Thread(target=self._health_loop,
                             name="tier-health", daemon=True),
            threading.Thread(target=self._supervisor_loop,
                             name="tier-supervisor", daemon=True),
        ]
        if self.roll_every_s > 0:
            self._threads.append(threading.Thread(
                target=self._roll_loop, name="tier-roll", daemon=True))
        for t in self._threads:
            t.start()
        self.ready.set()
        print(f"[tier] router on http://{self.host}:{self.bound_port} "
              f"over {len(self.replicas)} replica(s): "
              + ", ".join(h.url for h in self.replicas), flush=True)

    def wait_ready(self, n: int = 1, timeout: float = 180.0) -> bool:
        """Block until at least `n` replicas are routable."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if sum(1 for h in self.replicas if h.routable) >= n:
                return True
            if self.stopped.wait(0.05):
                return False
        return sum(1 for h in self.replicas if h.routable) >= n

    def close(self, *, replica_grace_s: float = 15.0) -> None:
        self.stopped.set()
        for h in self.replicas:
            proc = h.proc
            if proc is not None and proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)   # graceful drain
                except OSError:
                    pass
        deadline = time.monotonic() + replica_grace_s
        for h in self.replicas:
            proc = h.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=5)
        if self.logger is not None:
            self.logger.close()

    # -- supervision ---------------------------------------------------------

    def _launch(self, h: ReplicaHandle) -> None:
        env = dict(os.environ)
        env.update(h.env or {})
        # spawn BEFORE taking h.lock: process creation is slow I/O, and the
        # probe/routing threads must not stall behind it (LCK004 shape)
        proc = subprocess.Popen(h.argv, env=env)
        with h.lock:
            h.proc = proc
            h.pending_restart = False
            h.launches += 1
            launches = h.launches
            if launches > 1:
                h.breaker = h._fresh_breaker()
        if launches > 1:
            self._bump("restarts")
            self._event({"tier_replica_restarted": 1.0,
                         "replica_slot": float(h.slot),
                         "launches": float(launches)})
            print(f"[tier] replica {h.rid} restarted "
                  f"(launch #{launches}, pid {proc.pid}) — awaiting "
                  f"/healthz before re-admission", file=sys.stderr,
                  flush=True)

    def _supervisor_loop(self) -> None:
        while not self.stopped.wait(0.1):
            for h in self.replicas:
                if h.argv is None:
                    continue
                proc = h.proc
                if proc is not None and proc.poll() is not None:
                    code = proc.returncode
                    with h.lock:
                        h.proc = None
                        h.dead = True
                        h.healthy = False
                        h.exits += 1
                        h.last_exit_code = code
                        h.pending_restart = True
                        # backoff bookkeeping stays inside the lock: the
                        # probe thread's re-admission reset (_note_routable)
                        # races this doubling, and a lost update either
                        # stalls the restart or hot-loops it
                        backoff = h.backoff_s
                        h.next_restart_at = time.monotonic() + backoff
                        h.backoff_s = min(backoff * 2.0,
                                          self.restart_backoff_max_s)
                    self._bump("exits")
                    self._event({"tier_replica_exit": 1.0,
                                 "replica_slot": float(h.slot),
                                 "exit_code": float(code if code is not None
                                                    else -1)})
                    print(f"[tier] replica {h.rid} exited code={code} — "
                          f"restart in {backoff:g}s", file=sys.stderr,
                          flush=True)
                    self._note_routable(h, f"process exit code={code}")
                if (h.proc is None and h.pending_restart
                        and time.monotonic() >= h.next_restart_at
                        and not self.stopped.is_set()):
                    self._launch(h)

    # -- health --------------------------------------------------------------

    def _health_loop(self) -> None:
        while not self.stopped.wait(self.health_every_s):
            for h in self.replicas:
                self._probe(h)

    def _probe(self, h: ReplicaHandle) -> None:
        if h.argv is not None and h.proc is None:
            return     # nothing on that port; the supervisor owns the slot
        wait = h.breaker.reject_for()
        if wait is not None:
            # open circuit mid-cooldown: no probe this tick (reject_for
            # itself flips open->half_open and grants the probe slot when
            # the cooldown lapses)
            self._note_routable(h, "breaker open")
            return
        js = None
        try:
            code, js = _http_json(h.url + "/healthz",
                                  timeout=self.probe_timeout_s)
            ok = code == 200 and isinstance(js, dict)
        except Exception:  # noqa: BLE001 — any transport failure is a miss
            ok = False
        h.breaker.record(ok)
        if ok:
            models = js.get("models") or {}
            with h.lock:
                h.dead = False
                h.healthy = True
                h.draining = js.get("status") == "draining"
                h.queue_depth = int(js.get("queue_depth") or 0)
                h.workers = (sum(int(m.get("workers") or 1)
                                 for m in models.values()) or 1)
                h.last_health = js
                h.last_health_unix = time.time()
            self._note_routable(h, "draining" if h.draining else "")
        else:
            with h.lock:
                h.healthy = False
            self._note_routable(h, "health probe failed")

    def _roll_loop(self) -> None:
        while not self.stopped.wait(self.roll_every_s):
            self.roll.roll_once()

    # -- routing -------------------------------------------------------------

    def _pick(self, exclude) -> Optional[ReplicaHandle]:
        n = len(self.replicas)
        k = next(self._rr) % n      # rotate ties instead of pinning slot 0
        best, best_score = None, None
        for h in (self.replicas[k:] + self.replicas[:k]):
            if h in exclude or not h.routable:
                continue
            s = h.score()
            if best is None or s < best_score:
                best, best_score = h, s
        return best

    def forward_predict(self, path: str, body: bytes, headers_in) -> tuple:
        """Route + forward one predict request; returns
        (status, body_bytes, content_type, replica_or_None, request_id,
        attempts). Retries transport failures and replica-local refusals
        on the next admitted replica; authoritative answers (200/400/404)
        pass through."""
        rid = headers_in.get("X-Request-Id") or new_request_id()
        deadline_hdr = headers_in.get("X-Deadline-Ms")
        try:
            deadline_s = (float(deadline_hdr) / 1000.0 if deadline_hdr
                          else self.default_deadline_s)
        except ValueError:
            deadline_s = self.default_deadline_s
        t_end = time.monotonic() + deadline_s
        fwd_headers = {
            "Content-Type": headers_in.get("Content-Type",
                                           "application/json"),
            "X-Request-Id": rid,
        }
        if deadline_hdr:
            fwd_headers["X-Deadline-Ms"] = deadline_hdr
        self._bump("requests")
        tried: List[ReplicaHandle] = []
        last: Optional[tuple] = None     # retryable verdict kept as fallback
        attempts = 0
        while not self.stopped.is_set():
            h = self._pick(tried)
            if h is None:
                break
            remaining = t_end - time.monotonic()
            if remaining <= 0.01:
                break
            tried.append(h)
            attempts += 1
            if attempts > 1:
                self._bump("retries")
            timeout = remaining
            if self.attempt_timeout_s is not None and any(
                    o.routable for o in self.replicas
                    if o is not h and o not in tried):
                timeout = min(remaining, self.attempt_timeout_s)
            with h.lock:
                h.inflight += 1
            try:
                req = urllib.request.Request(
                    h.url + path, data=body, headers=fwd_headers,
                    method="POST")
                try:
                    with urllib.request.urlopen(
                            req, timeout=timeout) as resp:
                        data = resp.read()
                        h.breaker.record(True)
                        with h.lock:
                            h.routed += 1
                        return (resp.status, data,
                                resp.headers.get("Content-Type",
                                                 "application/json"),
                                h, rid, attempts)
                except urllib.error.HTTPError as e:
                    data = e.read()
                    ct = e.headers.get("Content-Type", "application/json")
                    if e.code in (500, 504):
                        # the replica's serving path failed on a live
                        # request: charge its breaker, try the next one
                        h.breaker.record(False)
                        with h.lock:
                            h.failures += 1
                        self._note_routable(h, f"http {e.code}")
                        last = (e.code, data, ct, h)
                        continue
                    if e.code == 503:
                        # replica-local refusal (draining / circuit_open /
                        # deadline_unmeetable): it ANSWERED — not a
                        # transport failure, but another replica may admit
                        try:
                            reason = str(json.loads(
                                data.decode()).get("error", ""))
                        except Exception:  # noqa: BLE001
                            reason = ""
                        if "drain" in reason.lower():
                            with h.lock:
                                h.draining = True
                            self._note_routable(h, "draining")
                        last = (e.code, data, ct, h)
                        continue
                    if e.code == 429:
                        # per-model backpressure: exactly the case where a
                        # less-loaded replica absorbs the spike
                        last = (e.code, data, ct, h)
                        continue
                    # 400/404/...: authoritative — the request itself is
                    # the problem; pass the replica's body through verbatim
                    h.breaker.record(True)
                    return (e.code, data, ct, h, rid, attempts)
                except Exception as e:  # noqa: BLE001 — URLError/reset/...
                    h.breaker.record(False)
                    with h.lock:
                        h.failures += 1
                    reason = getattr(e, "reason", e)
                    if isinstance(reason, ConnectionRefusedError) or \
                            isinstance(e, ConnectionRefusedError):
                        # dead socket: eject on the spot, no K-failure wait
                        with h.lock:
                            h.dead = True
                            h.healthy = False
                        self._note_routable(h, "connection refused")
                    else:
                        self._note_routable(h, f"transport: {type(e).__name__}")
                    continue
            finally:
                with h.lock:
                    h.inflight -= 1
        if last is not None:
            code, data, ct, h = last
            return (code, data, ct, h, rid, attempts)
        self._bump("no_replica")
        body503 = json.dumps({
            "error": "NoReplicaAvailable",
            "detail": "no admitted replica (all dead, draining, or "
                      "circuit-open) — retry after the next health window",
            "replicas": len(self.replicas),
            "routable": 0,
        }).encode()
        return (503, body503, "application/json", None, rid, attempts)

    # -- read endpoints ------------------------------------------------------

    def health_body(self) -> dict:
        reps = [h.describe() for h in self.replicas]
        routable = sum(1 for r in reps if r["routable"])
        served: List[str] = []
        for h in self.replicas:
            with h.lock:
                lh = h.last_health
            if lh and lh.get("models"):
                served = list(lh["models"])
                break
        status = ("ok" if routable == len(reps)
                  else "unavailable" if routable == 0 else "degraded")
        return {"status": status, "size": len(reps), "routable": routable,
                "served_models": served, "replicas": reps,
                # replicas whose autoscaler exhausted within-mesh workers
                # and wants a replica across meshes — the operator's (or a
                # supervisor's) add-a-slot signal, aggregated tier-wide
                "scale_out_wanted": [r["replica"] for r in reps
                                     if r.get("wants_scale_out")],
                "roll": self.roll.describe()}

    def stats_body(self) -> dict:
        with self._stats_lock:
            stats = dict(self.stats)
        return {**stats,
                "replicas": {h.rid: {"routed": h.routed,
                                     "failures": h.failures,
                                     "launches": h.launches,
                                     "inflight": h.inflight}
                             for h in self.replicas},
                "roll": self.roll.describe()}

    def metrics_text(self) -> str:
        texts: Dict[str, str] = {}
        for h in self.replicas:
            if h.dead or not h.healthy:
                continue
            try:
                req = urllib.request.Request(h.url + "/metrics")
                with urllib.request.urlopen(
                        req, timeout=self.probe_timeout_s) as resp:
                    texts[h.rid] = resp.read().decode()
            except Exception:  # noqa: BLE001 — scrape what answers
                continue
        merged = merge_expositions(texts)
        with self._stats_lock:
            stats = dict(self.stats)
        routable = sum(1 for h in self.replicas if h.routable)
        P = "deepvision_tier_"
        lines: List[str] = []
        _emit(lines, P + "replicas", "gauge",
              "Configured replica slots.", [("", {}, len(self.replicas))])
        _emit(lines, P + "routable_replicas", "gauge",
              "Replicas currently admitted for routing.",
              [("", {}, routable)])
        _emit(lines, P + "requests_total", "counter",
              "Requests accepted by the tier front door.",
              [("", {}, stats["requests"])])
        _emit(lines, P + "routed_total", "counter",
              "Requests answered, by replica.",
              [("", {"replica": h.rid}, h.routed) for h in self.replicas])
        _emit(lines, P + "retries_total", "counter",
              "Same-request retries on another replica.",
              [("", {}, stats["retries"])])
        _emit(lines, P + "no_replica_total", "counter",
              "Requests refused: no admitted replica.",
              [("", {}, stats["no_replica"])])
        _emit(lines, P + "replica_ejections_total", "counter",
              "Routing ejections (crash, wedge, drain, breaker).",
              [("", {}, stats["ejections"])])
        _emit(lines, P + "replica_readmissions_total", "counter",
              "Replicas re-admitted after ejection.",
              [("", {}, stats["readmissions"])])
        _emit(lines, P + "replica_restarts_total", "counter",
              "Supervised replica respawns, by replica.",
              [("", {"replica": h.rid}, max(0, h.launches - 1))
               for h in self.replicas])
        _emit(lines, P + "inflight", "gauge",
              "Router-side in-flight requests, by replica.",
              [("", {"replica": h.rid}, h.inflight)
               for h in self.replicas])
        return merged + "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *fmt_args):  # noqa: N802 — stdlib name
        pass

    def _send(self, code: int, body: bytes, content_type: str,
              extra: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            if v is not None:
                self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj, extra: Optional[dict] = None) -> None:
        self._send(code, json.dumps(obj).encode(), "application/json",
                   extra)

    def do_GET(self):  # noqa: N802 — stdlib handler name
        router = self.server.router
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            body = router.health_body()
            return self._json(200 if body["status"] != "unavailable"
                              else 503, body)
        if path == "/stats":
            return self._json(200, router.stats_body())
        if path == "/metrics":
            text = router.metrics_text().encode()
            return self._send(200, text,
                              "text/plain; version=0.0.4; charset=utf-8")
        if path == "/trace":
            return self._json(200, chrome_trace(router.tracer))
        return self._json(404, {"error": f"unknown path {self.path!r}"})

    def do_POST(self):  # noqa: N802 — stdlib handler name
        router = self.server.router
        path = self.path.split("?", 1)[0]
        if path == "/roll":
            return self._json(200, router.roll.roll_once())
        if path == "/predict" or path.startswith("/predict/"):
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            t0 = time.monotonic_ns()
            code, data, ct, h, rid, attempts = router.forward_predict(
                path, body, self.headers)
            router.tracer.add(
                "tier_route", "tier", t0, time.monotonic_ns() - t0,
                args={"request_id": rid, "status": code,
                      "attempts": attempts,
                      "replica": h.rid if h is not None else None})
            router._bump(f"responses_{code // 100}xx")
            extra = {"X-Request-Id": rid,
                     "X-Tier-Replica": h.rid if h is not None else None}
            if code == 503 and h is None:
                extra["Retry-After"] = max(
                    1, int(router.health_every_s + 0.999))
            return self._send(code, data, ct, extra)
        return self._json(404, {"error": f"unknown path {self.path!r}"})


# -- CLI ----------------------------------------------------------------------

def build_tier_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m deepvision_tpu.serve.tier",
        description="Replica-tier router: N supervised fleet-server "
                    "replicas behind one least-loaded front door with "
                    "circuit breaking, supervised restart, rolling "
                    "promotion, and merged /metrics.")
    p.add_argument("-m", "--model", required=True,
                   help="model name(s), comma separated — every replica "
                        "serves the same fleet")
    p.add_argument("--replicas", type=int, default=2,
                   help="replica process count (default 2)")
    p.add_argument("--port", type=int, default=8701,
                   help="router port (replica ports are OS-assigned)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--runs-root", default=None,
                   help="forwarded to replicas: per-model run dirs "
                        "(trained weights + hot-reload watching)")
    p.add_argument("--promote-gate", type=float, default=None,
                   help="forwarded to replicas: arm the accuracy gate; "
                        "candidates are then driven through the tier's "
                        "ROLLING promotion (POST /roll), replica by "
                        "replica")
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--buckets", default=None,
                   help="forwarded to replicas (default: replica default)")
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--max-delay-ms", type=float, default=None)
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="forwarded to replicas as the default deadline")
    p.add_argument("--cache-dir", default="auto",
                   help="persistent XLA compile cache dir SHARED by every "
                        "replica — the warm-boot contract (default: the "
                        "user cache dir, already shared)")
    p.add_argument("--drain-grace", type=float, default=0.75,
                   help="forwarded to replicas: seconds /healthz says "
                        "'draining' before the batcher drain starts")
    p.add_argument("--health-every", type=float, default=0.25,
                   help="router health-poll period, seconds")
    p.add_argument("--probe-timeout", type=float, default=1.0,
                   help="deadline on each health probe (bounds wedge "
                        "detection latency)")
    p.add_argument("--attempt-timeout", type=float, default=0.0,
                   help="per-replica attempt cap, seconds (0 = off): with "
                        "other replicas available, a forward that exceeds "
                        "this retries elsewhere instead of burning the "
                        "whole client deadline on a wedged replica")
    p.add_argument("--tier-breaker-k", type=int, default=3,
                   help="consecutive per-replica failures that open its "
                        "routing circuit")
    p.add_argument("--tier-breaker-cooldown", type=float, default=1.0,
                   help="seconds an open replica circuit waits before a "
                        "half-open probe")
    p.add_argument("--restart-backoff", type=float, default=0.5,
                   help="initial supervised-restart backoff, doubling to "
                        "8x (reset on readmission)")
    p.add_argument("--roll-every", type=float, default=0.0,
                   help="seconds between automatic rolling-promotion "
                        "sweeps (0 = only on POST /roll)")
    p.add_argument("--log-dir", default=None,
                   help="JSONL dir for resilience_tier_* events")
    p.add_argument("--smoke", action="store_true",
                   help="boot the tier, run synthetic HTTP load through "
                        "the router, print one JSON verdict, exit")
    p.add_argument("--duration", type=float, default=4.0,
                   help="--smoke load duration, seconds")
    p.add_argument("--smoke-threads", type=int, default=4)
    p.add_argument("--kill-one", action="store_true",
                   help="--smoke only: SIGKILL one replica mid-load and "
                        "require zero failed responses + a supervised "
                        "readmission")
    return p


def _replica_argv(args, slot: int, port: int) -> List[str]:
    argv = [sys.executable, "-m", "deepvision_tpu.serve.replica",
            "-m", args.model, "--port", str(port), "--host", args.host,
            "--replica-id", str(slot),
            "--compilation-cache", args.cache_dir,
            "--drain-grace", str(args.drain_grace)]
    if args.runs_root:
        argv += ["--runs-root", args.runs_root]
    if args.promote_gate is not None:
        argv += ["--promote-gate", str(args.promote_gate)]
    if args.image_size is not None:
        argv += ["--image-size", str(args.image_size)]
    if args.buckets:
        argv += ["--buckets", args.buckets]
    if args.max_batch is not None:
        argv += ["--max-batch", str(args.max_batch)]
    if args.max_delay_ms is not None:
        argv += ["--max-delay-ms", str(args.max_delay_ms)]
    if args.workers is not None:
        argv += ["--workers", str(args.workers)]
    if args.deadline_ms is not None:
        argv += ["--deadline-ms", str(args.deadline_ms)]
    return argv


def build_tier(args) -> TierRouter:
    """Replica handles (supervised, OS-assigned ports) + router from
    parsed `build_tier_parser` args."""
    handles = []
    for slot in range(args.replicas):
        port = free_port(args.host)
        handles.append(ReplicaHandle(
            str(slot), f"http://{args.host}:{port}",
            argv=_replica_argv(args, slot, port), slot=slot,
            breaker_k=args.tier_breaker_k,
            breaker_cooldown_s=args.tier_breaker_cooldown))
    first_model = args.model.split(",")[0].strip()
    return TierRouter(
        handles, host=args.host, port=args.port,
        health_every_s=args.health_every,
        probe_timeout_s=args.probe_timeout,
        attempt_timeout_s=args.attempt_timeout or None,
        restart_backoff_s=args.restart_backoff,
        roll_model=first_model, roll_every_s=args.roll_every,
        log_dir=args.log_dir)


def _smoke_payload(model: str) -> bytes:
    """One valid single-instance predict body for `model`, shaped from its
    registered config (H, W, C)."""
    from ..configs import get_config
    d = get_config(model).data
    row = [[0.5] * d.channels for _ in range(d.image_size)]
    instance = [row for _ in range(d.image_size)]
    return json.dumps({"instances": [instance]}).encode()


def _run_smoke(router: TierRouter, args) -> int:
    n = len(router.replicas)
    if not router.wait_ready(n=n, timeout=600):
        print(json.dumps({"tier_smoke": "fail",
                          "error": "replicas never became routable"}),
              flush=True)
        return 1
    payload = _smoke_payload(args.model.split(",")[0].strip())
    url = f"http://{router.host}:{router.bound_port}/predict"
    stop = threading.Event()
    failures: List[tuple] = []
    ok_count = itertools.count()

    def client(i: int) -> None:
        while not stop.is_set():
            try:
                req = urllib.request.Request(
                    url, data=payload,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    resp.read()
                    next(ok_count)
            except Exception as e:  # noqa: BLE001 — every miss is a verdict
                failures.append((i, repr(e)))

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.smoke_threads)]
    for t in threads:
        t.start()
    victim = None
    if args.kill_one:
        time.sleep(args.duration / 3.0)
        victim = router.replicas[0]
        if victim.proc is not None:
            print(f"[tier-smoke] SIGKILL replica {victim.rid} "
                  f"(pid {victim.proc.pid}) mid-load", file=sys.stderr,
                  flush=True)
            victim.proc.kill()
        time.sleep(2.0 * args.duration / 3.0)
        router.wait_ready(n=n, timeout=120)   # supervised back + readmitted
    else:
        time.sleep(args.duration)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    stats = router.stats_body()
    answered = next(ok_count)
    all_routed = all(h.routed > 0 for h in router.replicas)
    readmitted = (not args.kill_one) or (
        victim is not None and victim.routable and victim.launches >= 2)
    ok = (not failures and answered > 0 and all_routed and readmitted)
    print(json.dumps({
        "tier_smoke": "pass" if ok else "fail",
        "replicas": n, "answered": answered,
        "failed_responses": len(failures),
        "routed": {h.rid: h.routed for h in router.replicas},
        "retries": stats["retries"], "ejections": stats["ejections"],
        "readmissions": stats["readmissions"],
        "restarts": stats["restarts"],
        "killed": victim.rid if victim is not None else None,
        **({"first_failure": failures[0][1]} if failures else {}),
    }), flush=True)
    return 0 if ok else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_tier_parser()
    args = parser.parse_args(argv)
    if args.replicas < 1:
        parser.error(f"--replicas must be >= 1, got {args.replicas}")
    if args.kill_one and not args.smoke:
        parser.error("--kill-one is a --smoke option")
    router = build_tier(args)
    router.start()
    try:
        if args.smoke:
            return _run_smoke(router, args)
        with GracefulShutdown(
                on_signal=router.stopped.set,
                what="draining replicas, then exiting 0") as gs:
            while not gs.requested and not router.stopped.is_set():
                time.sleep(0.2)
        return 0
    finally:
        router.close()


if __name__ == "__main__":
    raise SystemExit(main())
