"""`python -m deepvision_tpu.serve.replica` — one replica of a serving tier.

A replica IS the standalone fleet server (serve/cli.py builds it through
the same `build_server`), launched with the small contract the tier router
(serve/tier.py) supervises it under:

- **Identity**: `--replica-id` is echoed on `/healthz` (`"replica"`), so
  the router can confirm the process answering a slot's port is the
  process it respawned into that slot.
- **Warm boot**: the router passes every replica the SAME persistent XLA
  compilation cache dir (`--compilation-cache`), so only the tier's FIRST
  boot ever compiles the bucket programs — a crashed replica's replacement
  (and every cold start after the first) reads its executables from the
  shared cache and is serving-warm in seconds. `/healthz` reports per-model
  compile hit/miss counts, so "zero recompiles on the warm path" is a fact
  the router (and bench_serve.py --tier) can check, not an assumption.
- **Graceful de-admission**: `--drain-grace` defaults to 0.75 s here
  (the standalone CLI defaults to 0): on SIGTERM `/healthz` flips to
  "draining" in the signal handler, then the replica keeps answering for
  the grace window so the router's health poll de-admits it BEFORE the
  batcher drain refuses anything — a drained replica costs zero 5xx.
- **Router-driven promotion**: `--promote-gate` is allowed WITHOUT
  `--reload-every` (the standalone CLI couples them): the replica arms the
  shadow/canary controller but never polls for candidates on its own —
  the router's rolling promotion drives `POST /reload` one replica at a
  time, so a regressing candidate is exposed on exactly one replica.
- **Fault rehearsal**: `DEEPVISION_FAULT_REPLICA_CRASH` /
  `DEEPVISION_FAULT_REPLICA_WEDGE` (utils/faults.py) are read from the
  environment by the server itself — the router's ejection paths are
  CI-rehearsable against a real replica process.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .cli import build_parser, build_server, validate_args


def build_replica_parser():
    p = build_parser()
    p.prog = "python -m deepvision_tpu.serve.replica"
    p.add_argument("--replica-id", default=None,
                   help="tier slot identity, echoed on /healthz — set by "
                        "the router (serve/tier.py) so it can verify which "
                        "replica answers a supervised slot's port")
    # replicas live behind a health-polling router: give its poll one
    # window to de-admit before the drain refuses work
    p.set_defaults(drain_grace=0.75)
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_replica_parser()
    args = parser.parse_args(argv)
    # the router triggers promotion via POST /reload; the replica's own
    # poller stays off unless explicitly armed
    validate_args(parser, args, require_reload_for_gate=False)
    server = build_server(args, replica_id=args.replica_id)
    try:
        server.serve(port=args.port, host=args.host)
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
