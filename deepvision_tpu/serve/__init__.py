"""Serving subsystem: dynamic-batching inference over the model zoo.

The training half of the stack (mesh-sharded steps, prefetch overlap,
resilience) is built; this package is the other half — turning a trained
checkpoint into something that takes traffic (docs/SERVING.md):

- engine.PredictEngine: shape-bucketed AOT-compiled predict cache
  (no per-request trace/compile; padding provably inert)
- batcher.DynamicBatcher: thread-safe micro-batching with deadline +
  max_batch flush, futures, and example-counted backpressure
- metrics.ServingMetrics: p50/p99, padding waste, batch fill, shed —
  flushed on the trainer's MetricsLogger stream
- fleet.ModelFleet: many models behind one process — per-model batcher +
  metrics, routed by registry name (`POST /predict/<model>`)
- autoscale.AutoscaleController / CircuitBreaker: overload control —
  shed-driven scaling of each model's dispatcher pool over the shared AOT
  bucket cache (zero recompiles), deadline admission control at the door,
  and per-model fail-fast circuit breaking (docs/SERVING.md "Overload
  control")
- reload.WeightReloader: hot weight reload — new integrity-verified
  epochs swap into live engines atomically, zero downtime, zero recompiles
- quantize.Quantizer / arm_int8: calibrated int8 serving behind a hard
  accuracy gate — int8 bucket twins compiled beside the bf16 cache
  (`--serve-precision int8`), refusal falls back to bf16 loudly, hot
  reload/promotion re-quantize with zero recompiles (docs/SERVING.md
  "Quantized serving")
- promote.PromotionController: accuracy-gated promotion — shadow eval of
  each candidate against the live generation on a pinned shard, a
  metric-delta gate, canary traffic routing, and p99/error auto-rollback,
  every decision on the resilience_ stream and /healthz
- server.InferenceServer: stdlib HTTP front-end + graceful SIGTERM drain
  (core/resilience.GracefulShutdown contract, exit 0)
- cli: `python -m deepvision_tpu.serve` (HTTP or --smoke; multi-model via
  `-m name1,name2 --runs-root runs/`)
"""

from .autoscale import AutoscaleController, CircuitBreaker  # noqa: F401
from .batcher import (CircuitOpen, DeadlineExpired,  # noqa: F401
                      DeadlineUnmeetable, Draining, DynamicBatcher,
                      Overloaded, RequestRejected, result_within)
from .engine import PredictEngine, load_checkpoint_weights, pick_bucket  # noqa: F401
from .fleet import ModelFleet, ServedModel, UnknownModel  # noqa: F401
from .metrics import ServingMetrics  # noqa: F401
from .promote import PromotionController, pinned_eval_shard  # noqa: F401
from .quantize import Quantizer, arm_int8  # noqa: F401
from .reload import WeightReloader  # noqa: F401
from .server import InferenceServer  # noqa: F401
