"""Shared CLI for the per-family training entrypoints.

Preserves the reference's documented UX (`python train.py -m <model> [-c <ckpt>]`,
`ResNet/pytorch/train.py:541-562`; `ResNet/pytorch/README.md:33`) while backing every
family's `train.py` with the shared trainers. Extras the reference lacked:
`--synthetic` smoke mode, `--data-dir`, epoch/batch overrides, auto-resume.

One `_run` driver covers all task types; each task contributes only its trainer
class and a `make_data(cfg, args)` hook returning `(train_fn, val_fn)` epoch-data
factories.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
from typing import Callable, Optional, Sequence, Tuple

from .configs import CONFIGS, get_config

SYNTH_STEPS_DEFAULT = 8


def _positive_int(s: str) -> int:
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return v


def build_parser(family: str, models: Sequence[str]) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=f"Train {family} models (TPU-native JAX). Models: {', '.join(models)}")
    p.add_argument("-m", "--model", required=True, choices=list(models))
    p.add_argument("-c", "--checkpoint", default=None,
                   help="resume from this epoch number, or 'latest'")
    p.add_argument("--data-dir", default=None,
                   help="dataset root (TFRecords for ImageNet/VOC/COCO/MPII, "
                        "idx files for MNIST)")
    p.add_argument("--synthetic", action="store_true",
                   help="train on synthetic data (smoke test, no dataset needed)")
    p.add_argument("--dataset", default=None,
                   help="override the config's dataset flavor (e.g. "
                        "imagenet_flat for the reference's flattened-dir "
                        "layout instead of TFRecords)")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--eval-batch-size", type=int, default=None,
                   help="validation batch size (defaults to --batch-size)")
    p.add_argument("--learning-rate", type=float, default=None,
                   help="override the config's base learning rate")
    p.add_argument("--accum-steps", type=_positive_int, default=None,
                   help="gradient accumulation: average grads over k "
                        "micro-batches per optimizer update (effective batch "
                        "= batch-size * k)")
    p.add_argument("--log-grad-norm", action="store_true",
                   help="log the global L2 gradient norm per step (divergence "
                        "forensics; informs grad_clip_norm)")
    p.add_argument("--no-halt-on-nonfinite", action="store_true",
                   help="keep training after a NaN/inf epoch loss instead of "
                        "halting with the last-good checkpoint (divergence "
                        "guard is on by default)")
    p.add_argument("--no-decay-bn-bias", action="store_true",
                   help="skip weight decay on BatchNorm scales/biases and "
                        "layer biases (large-batch recipe; default keeps the "
                        "reference's decay-everything SGD semantics)")
    p.add_argument("--ema-decay", type=float, default=None,
                   help="Polyak averaging: validate/select-best with the "
                        "EMA of the weights (typical 0.999-0.9999)")
    p.add_argument("--mixup-alpha", type=float, default=None,
                   help="mixup augmentation strength (classification; "
                        "lam ~ Beta(a, a), typical 0.1-0.4)")
    p.add_argument("--cutmix-alpha", type=float, default=None,
                   help="CutMix augmentation strength (classification; "
                        "pasted-box blending, typical 1.0; exclusive with "
                        "--mixup-alpha)")
    p.add_argument("--num-classes", type=int, default=None,
                   help="override output classes/keypoints (e.g. MPII=16 "
                        "heatmaps, custom VOC subsets)")
    p.add_argument("--workdir", default=None)
    p.add_argument("--steps-per-epoch", type=int, default=None,
                   help="override steps per epoch (synthetic/smoke)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write a Chrome/Perfetto trace-event JSON of the "
                        "run on exit: per log-window spans splitting host "
                        "data wait vs device dispatch vs checkpoint commit, "
                        "tagged with the prefetch transfer ledger "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the first epoch here")
    p.add_argument("--seed", type=int, default=None,
                   help="override the config's PRNG seed")
    p.add_argument("--auto-resume", action="store_true",
                   help="resume from the latest checkpoint if one exists "
                        "(preemption recovery; starts fresh otherwise). "
                        "Elastic: the checkpoint may come from a DIFFERENT "
                        "mesh shape — a run preempted on N chips resumes on "
                        "M, or with --model-parallel/--spatial-parallel "
                        "changed; restore reshards against the integrity "
                        "manifest and the next save re-stamps the current "
                        "mesh (docs/FAILURES.md 'Elastic resume')")
    p.add_argument("--resume", choices=["strict", "fallback"], default=None,
                   help="checkpoint integrity mode for -c/--auto-resume: "
                        "'fallback' (default) verifies the integrity "
                        "manifest and on corruption quarantines the bad "
                        "epoch (corrupt-<N>/) and resumes from the next-"
                        "newest epoch that verifies; 'strict' refuses to "
                        "restore an unverified checkpoint (docs/FAILURES.md; "
                        "audit with `python -m deepvision_tpu fsck`). Both "
                        "modes reshard a checkpoint saved under a different "
                        "mesh shape — the manifest's verified per-leaf "
                        "shapes/hashes are the re-slicing source of truth")
    p.add_argument("--recover-on-divergence", type=int, default=None,
                   metavar="N",
                   help="when an epoch's loss goes non-finite, roll back to "
                        "the last committed checkpoint, scale the LR down, "
                        "and retry — up to N times before halting with the "
                        "usual divergence error (default 0: halt only)")
    p.add_argument("--watchdog-secs", type=float,
                   default=os.environ.get("DEEPVISION_WATCHDOG_SECS"),
                   metavar="S",
                   help="in-process stall watchdog: abort (exit 70) with "
                        "diagnostics when no train step completes for S "
                        "seconds — set S above the first-step compile time; "
                        "default off (env DEEPVISION_WATCHDOG_SECS)")
    p.add_argument("--no-graceful-shutdown", action="store_true",
                   help="disable the SIGTERM/SIGINT handler that commits a "
                        "checkpoint and exits 0 on preemption (on by "
                        "default; SIGKILL atomicity is unaffected either "
                        "way)")
    p.add_argument("--model-parallel", type=int, default=None,
                   help="mesh 'model' axis size (shard big params / matmuls)")
    p.add_argument("--spatial-parallel", type=int, default=None,
                   help="mesh 'spatial' axis size: shard activations along "
                        "image height (context parallelism; GSPMD "
                        "halo-exchanges the convs)")
    p.add_argument("--spatial-backend", choices=["gspmd", "shard_map"],
                   default=None,
                   help="who owns the spatial partitioning semantics: the "
                        "XLA partitioner ('gspmd', default) or explicit "
                        "shard_map collectives ('shard_map': exact on "
                        "combined spatial x model meshes, no calibration; "
                        "ResNet family, MobileNet, CenterNet, Hourglass "
                        "pose, YOLO)")
    p.add_argument("--device-normalize", action="store_true",
                   help="ship raw uint8 pixels to the device and normalize "
                        "inside the jitted step (4x less host->device "
                        "traffic; TFRecord pipelines: ImageNet / "
                        "detection / pose)")
    p.add_argument("--device-augment", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="classification: host decodes + resizes to a padded "
                        "uint8 square and RandomCrop/flip/ColorJitter/"
                        "normalize run batched inside the jitted train step "
                        "(~4x less host->device traffic AND no host "
                        "augmentation CPU; per-step PRNG keys keep runs "
                        "seed-reproducible — docs/INPUT_PIPELINE.md; "
                        "synthetic / imagenet / imagenet_flat pipelines)")
    p.add_argument("--cache-val", action="store_true",
                   help="cache the validation records in host RAM after the "
                        "first epoch (classification ImageNet TFRecords)")
    p.add_argument("--steps-per-dispatch", type=_positive_int, default=None,
                   help="run k train steps per host dispatch via a device-"
                        "side lax.scan — amortizes dispatch latency "
                        "(relayed TPUs, small steps); metrics surface as "
                        "the k-step mean; incompatible with --accum-steps")
    p.add_argument("--prefetch-batches", type=_positive_int, default=None,
                   help="stage this many training batches ahead on device "
                        "from a producer thread (default 2; 1 disables)")
    p.add_argument("--epoch-on-device", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="whole-epoch on-device training: stage the full "
                        "epoch device-resident once and run ONE lax.scan "
                        "dispatch per epoch — zero host round-trips (the "
                        "endpoint of the --steps-per-dispatch axis). In-"
                        "memory datasets only (synthetic/mnist/digits/"
                        "seg scenes); per-epoch reshuffle happens on device "
                        "folded from (seed, epoch); an epoch that exceeds "
                        "the HBM budget falls back to the staged path with "
                        "a named warning (docs/INPUT_PIPELINE.md)")
    p.add_argument("--eval-only", action="store_true",
                   help="restore (-c/--auto-resume) and run validation once; "
                        "no training")
    p.add_argument("--multihost", action="store_true",
                   help="force jax.distributed.initialize() (auto-detected "
                        "when a coordinator address env var is set)")
    p.add_argument("--compilation-cache",
                   default=os.environ.get("DEEPVISION_COMPILATION_CACHE",
                                          "auto"),
                   metavar="DIR|off",
                   help="persistent XLA compilation cache: restarted runs "
                        "(resume after preemption, --eval-only) skip the "
                        "20-40s TPU compile. 'auto' (default, or env "
                        "DEEPVISION_COMPILATION_CACHE) uses "
                        "~/.cache/deepvision_tpu/xla; 'off' disables")
    return p


# -- persistent-compile-cache accounting --------------------------------------
# jax emits monitoring events per compile when the persistent cache is
# consulted (/jax/compilation_cache/compile_requests_use_cache), per hit
# (.../cache_hits) and a compile_time_saved_sec duration per hit. Counting
# them makes repeat runs SAY whether they re-paid compile time — a silent
# cache regression (moved dir, changed key) otherwise just reads as "the TPU
# felt slow today" (the bench-attempt lesson this satellite exists for).
_cache_counts = {"requests": 0, "hits": 0, "saved_s": 0.0}
_cache_hooks_installed = False


def install_cache_stats_hooks() -> None:
    """Idempotently register the monitoring listeners behind
    `compilation_cache_stats` and an at-exit one-line report (stderr, only
    when at least one cache-consulting compile happened)."""
    global _cache_hooks_installed
    if _cache_hooks_installed:
        return
    _cache_hooks_installed = True
    import atexit

    from jax import monitoring

    def _on_event(event, **kw):
        if event == "/jax/compilation_cache/compile_requests_use_cache":
            _cache_counts["requests"] += 1
        elif event == "/jax/compilation_cache/cache_hits":
            _cache_counts["hits"] += 1

    def _on_duration(event, duration, **kw):
        if event == "/jax/compilation_cache/compile_time_saved_sec":
            _cache_counts["saved_s"] += duration

    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)
    atexit.register(_report_cache_stats)


def compilation_cache_stats() -> dict:
    """{'hits','misses','time_saved_s'} since the hooks went in. A "miss" is
    a cache-consulting compile that found no entry — including compiles under
    the persistence threshold (they consult, miss, and are not written)."""
    h, r = _cache_counts["hits"], _cache_counts["requests"]
    return {"hits": h, "misses": max(0, r - h),
            "time_saved_s": round(_cache_counts["saved_s"], 2)}


def _report_cache_stats() -> None:
    s = compilation_cache_stats()
    if s["hits"] or s["misses"]:
        print(f"[compile-cache] hits={s['hits']} misses={s['misses']} "
              f"compile_time_saved={s['time_saved_s']}s", file=sys.stderr,
              flush=True)


def setup_compilation_cache(arg: str = None) -> None:
    """Point JAX's persistent compilation cache at a durable directory, so a
    relaunched process (auto-resume after preemption — SURVEY.md §5.3 — or a
    second --eval-only run) reuses compiled executables instead of paying the
    first-compile latency again. 'off' also unsets a cache dir enabled by an
    earlier run in this process. An unwritable cache path degrades to no
    caching, never to a failed run. arg=None (the non-CLI callers: bench.py,
    bench_dispatch, dryrun_multichip) reads DEEPVISION_COMPILATION_CACHE
    from the env, defaulting to 'auto' — ONE place owns that idiom."""
    import jax
    if arg is None:
        arg = os.environ.get("DEEPVISION_COMPILATION_CACHE", "auto")

    def _reset_singleton():
        # jax's persistent cache initializes lazily ONCE with the dir in
        # effect at first use; a later jax.config.update alone is silently
        # ignored. Changing (or disabling) the dir mid-process must reset
        # the singleton or the switch is a no-op.
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as cc)
            cc.reset_cache()
        except Exception:
            pass  # no cache initialized yet / API moved — config still set

    if arg == "off":
        jax.config.update("jax_compilation_cache_dir", None)
        _reset_singleton()
        return
    path = (os.path.join(os.path.expanduser("~"), ".cache", "deepvision_tpu",
                         "xla") if arg == "auto" else arg)
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        # "degrades to no caching" means exactly that — also drop any cache
        # enabled earlier in this process, or the bad path silently keeps
        # reading/writing the old dir
        print(f"compilation cache disabled ({e})", flush=True)
        jax.config.update("jax_compilation_cache_dir", None)
        _reset_singleton()
        return
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ.get("DEEPVISION_CACHE_MIN_COMPILE_SECS", "1.0")))
    _reset_singleton()
    install_cache_stats_hooks()


def _tfrecord_data(build_dataset: Callable, cfg, args, default_dir: str,
                   bounded_train_steps: bool = False,
                   builder_hint: str = ""):
    """Per-host train*/val* TFRecord pipelines shared by the tf.data tasks."""
    import jax

    from .data.imagenet import _tf, epoch_iterator
    data = cfg.data
    data_dir = args.data_dir or data.data_dir or default_dir

    def _check(pattern):
        # fail NOW with a remedy, not a tf.data NotFoundError mid-epoch.
        # tf.io.gfile.glob is the pipeline's own resolver (list_files), so
        # remote filesystems (gs://, s3://) pass the same way local dirs do.
        if not _tf().io.gfile.glob(pattern):
            hint = f" Build them with {builder_hint}." if builder_hint else ""
            raise SystemExit(
                f"no TFRecords match {pattern!r} — point --data-dir at the "
                f"dataset (or use --synthetic for a smoke run).{hint}")

    per_host = cfg.batch_size // jax.process_count()
    eval_per_host = (cfg.eval_batch_size or cfg.batch_size) // jax.process_count()
    common = dict(image_size=data.image_size,
                  num_process=jax.process_count(),
                  process_index=jax.process_index())
    _check(os.path.join(data_dir, "val*"))
    val_ds = build_dataset(os.path.join(data_dir, "val*"), training=False,
                           batch_size=eval_per_host, **common)
    if getattr(args, "eval_only", False):
        def val_fn(epoch, _ds=val_ds):
            return epoch_iterator(_ds)
        return _no_train_data, val_fn
    _check(os.path.join(data_dir, "train*"))
    train_ds = build_dataset(os.path.join(data_dir, "train*"), training=True,
                             batch_size=per_host, **common)
    # imagenet repeats its dataset → always bound each epoch; detection/pose
    # datasets are single-pass per epoch (reference semantics) → iterate fully
    # unless --steps-per-epoch explicitly bounds them
    steps = args.steps_per_epoch
    if steps is None and bounded_train_steps:
        steps = data.train_examples // cfg.batch_size

    def train_fn(epoch, _ds=train_ds, _steps=steps):
        return epoch_iterator(_ds, _steps)

    def val_fn(epoch, _ds=val_ds):
        return epoch_iterator(_ds)

    return train_fn, val_fn


def _run(family: str, models: Sequence[str], trainer_factory: Callable,
         make_data: Callable, argv: Optional[Sequence[str]] = None,
         synthetic_image_size: Optional[int] = None) -> dict:
    """Shared driver: parse → config overrides → trainer → data → fit."""
    args = build_parser(family, models).parse_args(argv)
    setup_compilation_cache(args.compilation_cache)

    from .parallel.mesh import maybe_init_distributed
    maybe_init_distributed(force=args.multihost)

    cfg = get_config(args.model)
    if args.epochs:
        cfg = cfg.replace(total_epochs=args.epochs)
    if args.batch_size:
        cfg = cfg.replace(batch_size=args.batch_size)
    if args.eval_batch_size:
        cfg = cfg.replace(eval_batch_size=args.eval_batch_size)
    if args.learning_rate:
        # an explicit LR is honored verbatim: clear base_batch_size so the
        # linear-scaling rule doesn't silently rescale it
        cfg = cfg.replace(optimizer=dataclasses.replace(
            cfg.optimizer, learning_rate=args.learning_rate,
            base_batch_size=None))
    if args.accum_steps:
        cfg = cfg.replace(optimizer=dataclasses.replace(
            cfg.optimizer, accum_steps=args.accum_steps))
    if args.log_grad_norm:
        cfg = cfg.replace(log_grad_norm=True)
    if args.no_halt_on_nonfinite:
        cfg = cfg.replace(halt_on_nonfinite=False)
    if args.no_decay_bn_bias:
        cfg = cfg.replace(optimizer=dataclasses.replace(
            cfg.optimizer, no_decay_bn_bias=True))
    if args.ema_decay is not None:
        if not 0.0 < args.ema_decay < 1.0:
            raise SystemExit(f"--ema-decay must be in (0, 1), got {args.ema_decay}")
        cfg = cfg.replace(ema_decay=args.ema_decay)
    if args.mixup_alpha is not None:
        if args.mixup_alpha < 0.0:
            raise SystemExit(f"--mixup-alpha must be >= 0, got {args.mixup_alpha}")
        cfg = cfg.replace(mixup_alpha=args.mixup_alpha)
    if args.cutmix_alpha is not None:
        if args.cutmix_alpha < 0.0:
            raise SystemExit(f"--cutmix-alpha must be >= 0, got {args.cutmix_alpha}")
        cfg = cfg.replace(cutmix_alpha=args.cutmix_alpha)
    if cfg.mixup_alpha > 0.0 and cfg.cutmix_alpha > 0.0:
        raise SystemExit("--mixup-alpha and --cutmix-alpha are mutually "
                         "exclusive; pass one of them")
    if args.num_classes:
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, num_classes=args.num_classes))
    if args.dataset:
        over = {"dataset": args.dataset}
        if args.dataset == "mnist":
            over.update(image_size=32, channels=1)  # pipeline pads 28→32, grayscale
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, **over))
    if getattr(args, "device_normalize", False):
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, normalize_on_device=True))
    if getattr(args, "device_augment", None) is not None:
        cfg = cfg.replace(device_augment=args.device_augment)
    if getattr(args, "cache_val", False):
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, cache_val=True))
    if args.steps_per_dispatch:
        cfg = cfg.replace(steps_per_dispatch=args.steps_per_dispatch)
    if args.prefetch_batches:
        cfg = cfg.replace(prefetch_batches=args.prefetch_batches)
    if getattr(args, "epoch_on_device", None) is not None:
        cfg = cfg.replace(epoch_on_device=args.epoch_on_device)
    if args.seed is not None:
        cfg = cfg.replace(seed=args.seed)
    if args.resume:
        cfg = cfg.replace(resume_verify=args.resume)
    if args.recover_on_divergence is not None:
        if args.recover_on_divergence < 0:
            raise SystemExit(f"--recover-on-divergence must be >= 0, got "
                             f"{args.recover_on_divergence}")
        cfg = cfg.replace(recover_on_divergence=args.recover_on_divergence)
    if args.watchdog_secs is not None:
        secs = float(args.watchdog_secs)
        if secs <= 0:
            raise SystemExit(f"--watchdog-secs must be > 0, got {secs}")
        cfg = cfg.replace(watchdog_secs=secs)
    if args.no_graceful_shutdown:
        cfg = cfg.replace(graceful_shutdown=False)
    if args.model_parallel:
        cfg = cfg.replace(model_parallel=args.model_parallel)
    if args.spatial_parallel:
        cfg = cfg.replace(spatial_parallel=args.spatial_parallel)
    if getattr(args, "spatial_backend", None):
        cfg = cfg.replace(spatial_backend=args.spatial_backend)
    if args.synthetic:
        n_batches = args.steps_per_epoch or SYNTH_STEPS_DEFAULT
        synth = dict(dataset="synthetic",
                     train_examples=cfg.batch_size * n_batches)
        if synthetic_image_size:
            synth["image_size"] = synthetic_image_size
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, **synth))
    if cfg.epoch_on_device:
        # the cache stages ONE epoch device-resident and replays it — only
        # the in-memory datasets are epoch-stationary and HBM-plausible;
        # the streaming pipelines keep the double-buffered staged default
        cacheable = {"synthetic", "seg_synthetic", "mnist", "digits",
                     "digits_seg", "digits_detect"}
        if cfg.data.dataset not in cacheable:
            raise SystemExit(
                f"--epoch-on-device caches one epoch device-resident and "
                f"needs an in-memory, epoch-stationary dataset "
                f"({', '.join(sorted(cacheable))}); dataset="
                f"{cfg.data.dataset!r} streams from disk — use the default "
                f"double-buffered staged path (--prefetch-batches) there")
        if cfg.data.dataset in ("digits_seg", "digits_detect"):
            # these pipelines re-COMPOSE scenes each epoch; under the cache
            # that becomes "epoch 1's scenes, device-reshuffled" — say so
            print(f"[{cfg.name}] --epoch-on-device: {cfg.data.dataset} "
                  f"normally re-composes scenes per epoch; the cache "
                  f"replays epoch 1's scenes with a device-side (seed, "
                  f"epoch) reshuffle instead", flush=True)
    workdir = args.workdir or os.path.join("runs", cfg.name)

    trainer = trainer_factory(cfg, workdir)
    if args.trace_out:
        trainer.arm_tracing(args.trace_out)
    train_fn, val_fn = make_data(cfg, args)

    # mnist pipeline pads 28→32, matching the configured image_size
    sample_shape = (cfg.data.image_size, cfg.data.image_size, cfg.data.channels)
    trainer.init_state(sample_shape)
    restored = None
    if args.checkpoint:
        restored = trainer.resume(
            None if args.checkpoint == "latest" else int(args.checkpoint))
    elif args.auto_resume:
        # preemption recovery (SURVEY.md §5.3): latest checkpoint if present,
        # fresh start otherwise — resume() returns None when the dir is empty
        restored = trainer.resume()
    if args.eval_only:
        if restored is None:
            # random weights would print a plausible-looking number; the
            # whole point of --eval-only is checking a restored checkpoint
            raise SystemExit(
                "--eval-only requires a restored checkpoint: pass -c "
                f"latest|N (and check --workdir; nothing restorable in "
                f"{trainer.workdir!r})")
        # evaluate a restored (e.g. imported) checkpoint without training —
        # the tail of the migration workflow: import_torch_checkpoint.py
        # then `train.py -m <model> -c latest --eval-only`
        result = trainer.evaluate(val_fn(0))
        trainer.close()
        print("eval: " + " ".join(f"{k}={v:.4f}" for k, v in result.items()))
        return result
    from .core.trainer import fit_and_close
    result = fit_and_close(trainer, train_fn, val_fn, sample_shape=sample_shape,
                           profile_dir=args.profile_dir)
    print(f"done: best={result.get('best_metric')}")
    return result


def _no_train_data(epoch):
    raise RuntimeError("training data was not built (--eval-only)")


def _array_pair_fns(cfg, args, *, train_xy, test_xy):
    """(train_fn, val_fn) over in-memory (images, labels) arrays — the shared
    shape of the mnist/digits pipelines. train_xy=None (--eval-only) installs
    the _no_train_data guard."""
    from .data.mnist import MnistBatches
    test_x, test_y = test_xy
    if train_xy is None:
        train_fn = _no_train_data
    else:
        train_x, train_y = train_xy

        def train_fn(epoch):
            return MnistBatches(train_x, train_y, cfg.batch_size,
                                shuffle=True, seed=epoch)

    def val_fn(epoch):
        return MnistBatches(test_x, test_y,
                            cfg.eval_batch_size or cfg.batch_size,
                            shuffle=False, drop_remainder=False)

    return train_fn, val_fn


def _synthetic_data(cfg, make_batches: Callable):
    """Shared synthetic train/val factories: `make_batches(steps, seed)`."""
    n_batches = max(1, cfg.data.train_examples // cfg.batch_size)
    return (lambda epoch: make_batches(n_batches, epoch),
            lambda epoch: make_batches(2, 10**6))


# -- classification ------------------------------------------------------------

def _classification_data(cfg, args):
    data = cfg.data
    # note: --synthetic already rewrote data.dataset to "synthetic" in _run,
    # so synthetic smoke runs are rejected here too (random floats were never
    # [0,255] pixels). device_augment subsumes normalize_on_device (the fused
    # augment normalizes), so the uint8 pipelines below satisfy both flags.
    if (data.normalize_on_device and not cfg.device_augment
            and data.dataset != "imagenet"):
        raise SystemExit(
            "--device-normalize is supported by the TFRecord ImageNet "
            f"pipeline only (dataset={data.dataset!r} normalizes on host)")
    if cfg.device_augment and data.dataset not in (
            "synthetic", "imagenet", "imagenet_flat"):
        raise SystemExit(
            "--device-augment needs a host-decode-only pipeline: synthetic, "
            f"imagenet (TFRecords), or imagenet_flat — dataset="
            f"{data.dataset!r} ships pre-transformed float batches")
    if args.synthetic or data.dataset == "synthetic":
        from .core.config import decode_image_size
        from .data.synthetic import SyntheticClassification
        if cfg.device_augment:
            # uint8 at the padded decode size — the same staging contract
            # the real decode-only loaders emit
            return _synthetic_data(
                cfg, lambda steps, seed: SyntheticClassification(
                    cfg.batch_size, decode_image_size(data.image_size),
                    data.channels, data.num_classes, steps, seed=seed,
                    emit_uint8=True))
        return _synthetic_data(cfg, lambda steps, seed: SyntheticClassification(
            cfg.batch_size, data.image_size, data.channels, data.num_classes,
            steps, seed=seed))
    elif data.dataset == "mnist":
        from .data.mnist import load_split
        data_dir = args.data_dir or data.data_dir or "dataset/mnist"
        train_fn, val_fn = _array_pair_fns(
            cfg, args,
            train_xy=(None if getattr(args, "eval_only", False)
                      else load_split(data_dir, "train")),
            test_xy=load_split(data_dir, "test"))
    elif data.dataset == "digits":
        from .data.digits import load_splits
        train_xy, test_xy = load_splits(data.image_size)
        if getattr(args, "eval_only", False):
            train_xy = None
        train_fn, val_fn = _array_pair_fns(cfg, args, train_xy=train_xy,
                                           test_xy=test_xy)
    elif data.dataset == "imagenet":
        from .data import imagenet as inet

        def build(pattern, *, training, **kw):
            if not training and data.cache_val:
                kw["cache"] = True  # val records cached after the first epoch
            return inet.build_dataset(
                pattern, training=training,
                normalize_on_host=not data.normalize_on_device,
                host_decode_only=cfg.device_augment,
                mean=data.mean, std=data.std, **kw)

        return _tfrecord_data(
            build, cfg, args, "dataset/tfrecord", bounded_train_steps=True,
            builder_hint="Datasets/ILSVRC2012/build_imagenet_tfrecord.py")
    elif data.dataset == "imagenet_flat":
        # the reference's flat-dir layout (`ResNet/pytorch/data_load.py:20-44`:
        # dataset/{train_flatten,val_flatten}/ + synsets.txt)
        import itertools

        import jax

        from .data.imagenet_flat import FlatImageNet
        data_dir = args.data_dir or data.data_dir or "dataset"
        synsets = os.path.join(data_dir, "synsets.txt")
        common = dict(image_size=data.image_size,
                      num_shards=jax.process_count(),
                      shard_index=jax.process_index(),
                      host_decode_only=cfg.device_augment)
        steps = args.steps_per_epoch
        # one instance per split: the directory scan happens once, and
        # FlatImageNet reshuffles internally on each __iter__ (epoch bump)
        val_ds = FlatImageNet(
            os.path.join(data_dir, "val_flatten"), synsets, training=False,
            batch_size=(cfg.eval_batch_size or cfg.batch_size)
            // jax.process_count(), **common)
        if getattr(args, "eval_only", False):
            train_fn = _no_train_data
        else:
            train_ds = FlatImageNet(
                os.path.join(data_dir, "train_flatten"), synsets,
                training=True,
                batch_size=cfg.batch_size // jax.process_count(), **common)

            def train_fn(epoch, _ds=train_ds, _steps=steps):
                return itertools.islice(iter(_ds), _steps) if _steps else _ds

        def val_fn(epoch, _ds=val_ds):
            return _ds
    else:
        raise ValueError(f"unknown dataset {data.dataset!r}")
    return train_fn, val_fn


def run_classification(family: str, models: Sequence[str],
                       argv: Optional[Sequence[str]] = None) -> dict:
    from .core.trainer import Trainer
    return _run(family, models, lambda c, w: Trainer(c, workdir=w),
                _classification_data, argv)


# -- detection -----------------------------------------------------------------

def _guard_device_normalize_synthetic(cfg, args):
    """--device-normalize needs a pipeline that can emit raw uint8; the
    synthetic generators yield floats that were never [0,255] pixels."""
    if cfg.data.normalize_on_device and (args.synthetic
                                         or cfg.data.dataset == "synthetic"):
        raise SystemExit("--device-normalize is incompatible with synthetic "
                         "data (random floats were never raw pixels)")


def _detection_data(cfg, args):
    import functools

    from .data import detection as det
    data = cfg.data
    _guard_device_normalize_synthetic(cfg, args)
    if args.synthetic or data.dataset == "synthetic":
        return _synthetic_data(cfg, lambda steps, seed: det.synthetic_batches(
            batch_size=cfg.batch_size, image_size=data.image_size,
            num_classes=data.num_classes, steps=steps, seed=seed))
    if data.dataset == "digits_detect":
        # real scanned digits composed into detection scenes — the offline
        # real-data detection gate (data/digits.py). Train scenes are
        # re-composed FRESH each epoch (composition is free, and scene
        # diversity — not scene repetition — is what makes the detector
        # generalize to the held-out handwriting); the val set is pinned
        # (seed 2, same identity ObjectsAsPoints/jax/evaluate.py rebuilds).
        if data.normalize_on_device:
            raise SystemExit("--device-normalize is incompatible with "
                             "digits_detect (scenes are already float "
                             "[-1,1], not raw pixels)")
        from .data.digits import (detection_batches, detection_scenes,
                                  detection_val_scenes, scan_splits)
        (tr_x, tr_y), _ = scan_splits()
        va = detection_val_scenes(canvas=data.image_size,
                                  n_scenes=data.val_examples)

        def _train(epoch):
            tr = detection_scenes(tr_x, tr_y, n_scenes=data.train_examples,
                                  canvas=data.image_size, seed=1000 + epoch)
            return detection_batches(tr, batch_size=cfg.batch_size,
                                     shuffle_seed=epoch)

        return _train, lambda epoch: detection_batches(
            va, batch_size=cfg.batch_size)
    if data.dataset != "detection":
        raise ValueError(f"detection families read 'detection' TFRecords, "
                         f"not dataset={data.dataset!r}")
    build = functools.partial(det.build_dataset,
                              normalize_on_host=not data.normalize_on_device)
    return _tfrecord_data(
        build, cfg, args, "dataset/tfrecords",
        builder_hint="Datasets/VOC2007|VOC2012|MSCOCO/tfrecords.py")


def run_detection(family: str, models: Sequence[str],
                  argv: Optional[Sequence[str]] = None) -> dict:
    """Detection (YOLO) entrypoint — `python train.py -m yolov3 [-c latest]`,
    mirroring `YOLO/tensorflow/train.py:276-313`'s `--checkpoint` resume UX."""
    from .core.detection import DetectionTrainer
    return _run(family, models, lambda c, w: DetectionTrainer(c, workdir=w),
                _detection_data, argv, synthetic_image_size=64)


# -- pose ----------------------------------------------------------------------

def _pose_data(cfg, args):
    import functools

    from .data import pose as pose_data
    data = cfg.data
    _guard_device_normalize_synthetic(cfg, args)
    if args.synthetic or data.dataset == "synthetic":
        return _synthetic_data(
            cfg, lambda steps, seed: pose_data.synthetic_batches(
                batch_size=cfg.batch_size, image_size=data.image_size,
                steps=steps, seed=seed))
    if data.dataset != "pose":
        raise ValueError(f"pose families read 'pose' TFRecords, "
                         f"not dataset={data.dataset!r}")
    build = functools.partial(pose_data.build_dataset,
                              normalize_on_host=not data.normalize_on_device)
    return _tfrecord_data(
        build, cfg, args, "dataset/tfrecords_mpii",
        builder_hint="Datasets/MPII/tfrecords_mpii.py")


def run_centernet(family: str, models: Sequence[str],
                  argv: Optional[Sequence[str]] = None) -> dict:
    """CenterNet entrypoint — same padded-GT detection data as YOLO; the
    reference never enabled its runner (`ObjectsAsPoints/tensorflow/train.py:248`)."""
    from .core.centernet import CenterNetTrainer
    # 128px minimum: stride-4 stem + order-5 hourglass needs 2^5 on the 1/4 grid
    return _run(family, models, lambda c, w: CenterNetTrainer(c, workdir=w),
                _detection_data, argv, synthetic_image_size=128)


def run_pose(family: str, models: Sequence[str],
             argv: Optional[Sequence[str]] = None) -> dict:
    """Pose (Hourglass) entrypoint — mirrors the reference's click CLI
    (`Hourglass/tensorflow/main.py:21-41`) with the shared `-m/-c` surface."""
    from .core.pose import PoseTrainer
    return _run(family, models, lambda c, w: PoseTrainer(c, workdir=w),
                _pose_data, argv, synthetic_image_size=64)


# -- segmentation ---------------------------------------------------------------

def _segmentation_data(cfg, args):
    from .data import segmentation as seg_data
    data = cfg.data
    if args.synthetic or data.dataset in ("synthetic", "seg_synthetic"):
        if data.normalize_on_device and not cfg.device_augment:
            raise SystemExit("--device-normalize is incompatible with the "
                             "synthetic segmentation backend (scenes are "
                             "already float [-1,1]); use --device-augment "
                             "for the uint8 pair staging contract")
        if cfg.device_augment:
            from .core.config import decode_image_size
            # paired uint8 image+mask at the padded decode size — the
            # staging contract of make_paired_train_augment
            return _synthetic_data(
                cfg, lambda steps, seed: seg_data.SyntheticSegmentation(
                    cfg.batch_size, decode_image_size(data.image_size),
                    data.channels, data.num_classes, steps, seed=seed,
                    emit_uint8=True))
        return _synthetic_data(
            cfg, lambda steps, seed: seg_data.SyntheticSegmentation(
                cfg.batch_size, data.image_size, data.channels,
                data.num_classes, steps, seed=seed))
    if data.dataset == "digits_seg":
        # real handwriting composed into segmentation scenes — the offline
        # real-data gate (data/segmentation.py). Train scenes re-compose
        # FRESH each epoch (scene diversity is the regularizer, exactly the
        # digits_detect convention); the val set stays pinned at seed 2.
        if cfg.device_augment or data.normalize_on_device:
            raise SystemExit("digits_seg ships float [-1,1] scenes — "
                             "--device-augment/--device-normalize need the "
                             "uint8 staging backends (seg_synthetic)")
        from .data.digits import scan_splits
        (tr_x, tr_y), _ = scan_splits()
        va = seg_data.segmentation_val_scenes(canvas=data.image_size,
                                              n_scenes=data.val_examples)

        def _train(epoch):
            tr = seg_data.segmentation_scenes(
                tr_x, tr_y, n_scenes=data.train_examples,
                canvas=data.image_size, seed=1000 + epoch)
            return seg_data.segmentation_batches(
                tr, batch_size=cfg.batch_size, shuffle_seed=epoch)

        return _train, lambda epoch: seg_data.segmentation_batches(
            va, batch_size=cfg.eval_batch_size or cfg.batch_size)
    raise ValueError(f"segmentation families read 'seg_synthetic' or "
                     f"'digits_seg' data, not dataset={data.dataset!r}")


def run_segmentation(family: str, models: Sequence[str],
                     argv: Optional[Sequence[str]] = None) -> dict:
    """Segmentation (U-Net) entrypoint — the dense-prediction family the
    reference zoo never had; same shared `-m/-c` surface as every other
    family (docs/SEGMENTATION.md)."""
    from .core.segment import SegmentationTrainer
    # 64px minimum: the unet_small encoder needs H/W divisible by 8, the
    # ResNet-50 encoder by 64 (stem + stages + stride-1 decoder alignment)
    return _run(family, models,
                lambda c, w: SegmentationTrainer(c, workdir=w),
                _segmentation_data, argv, synthetic_image_size=64)
