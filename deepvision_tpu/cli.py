"""Shared CLI for classification training.

Preserves the reference's documented UX (`python train.py -m <model> [-c <ckpt>]`,
`ResNet/pytorch/train.py:541-562`; `ResNet/pytorch/README.md:33`) while backing every
family's `train.py` with the one shared Trainer. Extras the reference lacked:
`--synthetic` smoke mode, `--data-dir`, epoch/batch overrides, auto-resume.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Optional, Sequence

from .configs import CONFIGS, get_config


def build_parser(family: str, models: Sequence[str]) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=f"Train {family} models (TPU-native JAX). Models: {', '.join(models)}")
    p.add_argument("-m", "--model", required=True, choices=list(models))
    p.add_argument("-c", "--checkpoint", default=None,
                   help="resume from this epoch number, or 'latest'")
    p.add_argument("--data-dir", default=None,
                   help="dataset root (TFRecords for ImageNet, idx files for MNIST)")
    p.add_argument("--synthetic", action="store_true",
                   help="train on synthetic data (smoke test, no dataset needed)")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--workdir", default=None)
    p.add_argument("--steps-per-epoch", type=int, default=None,
                   help="override steps per epoch (synthetic/smoke)")
    return p


def run_classification(family: str, models: Sequence[str],
                       argv: Optional[Sequence[str]] = None) -> dict:
    args = build_parser(family, models).parse_args(argv)
    cfg = get_config(args.model)
    if args.epochs:
        cfg = cfg.replace(total_epochs=args.epochs)
    if args.batch_size:
        cfg = cfg.replace(batch_size=args.batch_size)
    if args.synthetic:
        n_batches = args.steps_per_epoch or 8
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, dataset="synthetic", train_examples=cfg.batch_size * n_batches))
    workdir = args.workdir or os.path.join("runs", cfg.name)

    from .core.trainer import Trainer
    trainer = Trainer(cfg, workdir=workdir)

    data = cfg.data
    image_size = data.image_size
    if args.synthetic or data.dataset == "synthetic":
        from .data.synthetic import SyntheticClassification
        n_batches = max(1, data.train_examples // cfg.batch_size)

        def train_fn(epoch):
            return SyntheticClassification(cfg.batch_size, image_size, 3,
                                           data.num_classes, n_batches, seed=epoch)

        def val_fn(epoch):
            return SyntheticClassification(cfg.batch_size, image_size, 3,
                                           data.num_classes, 2, seed=10**6)

        sample_shape = (image_size, image_size, 3)
    elif data.dataset == "mnist":
        from .data.mnist import MnistBatches, load_split
        data_dir = args.data_dir or data.data_dir or "dataset/mnist"
        train_x, train_y = load_split(data_dir, "train")
        test_x, test_y = load_split(data_dir, "test")

        def train_fn(epoch):
            return MnistBatches(train_x, train_y, cfg.batch_size, shuffle=True,
                                seed=epoch)

        def val_fn(epoch):
            return MnistBatches(test_x, test_y, cfg.batch_size, shuffle=False,
                                drop_remainder=False)

        sample_shape = (32, 32, 1)
    elif data.dataset == "imagenet":
        import jax
        from .data import imagenet as inet
        data_dir = args.data_dir or data.data_dir or "dataset/tfrecord"
        per_host = cfg.batch_size // jax.process_count()
        steps = args.steps_per_epoch or data.train_examples // cfg.batch_size
        train_ds = inet.build_dataset(
            os.path.join(data_dir, "train*"), batch_size=per_host,
            image_size=image_size, training=True,
            num_process=jax.process_count(), process_index=jax.process_index())
        val_ds = inet.build_dataset(
            os.path.join(data_dir, "val*"), batch_size=per_host,
            image_size=image_size, training=False,
            num_process=jax.process_count(), process_index=jax.process_index())

        def train_fn(epoch, _ds=train_ds, _steps=steps):
            return inet.epoch_iterator(_ds, _steps)

        def val_fn(epoch, _ds=val_ds):
            return inet.epoch_iterator(_ds)

        sample_shape = (image_size, image_size, 3)
    else:
        raise ValueError(f"unknown dataset {data.dataset!r}")

    trainer.init_state(sample_shape)
    if args.checkpoint:
        trainer.resume(None if args.checkpoint == "latest" else int(args.checkpoint))
    result = trainer.fit(train_fn, val_fn, sample_shape=sample_shape)
    trainer.close()
    print(f"done: best={result.get('best_metric')}")
    return result
