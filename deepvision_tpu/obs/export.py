"""Trace + metrics exposition: Chrome trace-event JSON and Prometheus text.

Two consumers, zero dependencies:

- `chrome_trace(tracer)` / `write_chrome_trace(tracer, path)` render a
  `Tracer`'s ring as Chrome trace-event JSON — loadable in Perfetto
  (https://ui.perfetto.dev) or `chrome://tracing`. Complete ("X") events
  carry every span's args (request_id, bucket, generation, worker, ...);
  flow events ("s"/"f") draw the request→batch arrows so one request's
  queue wait visually lands in the device batch that served it. Serving
  exposes this as `GET /trace?secs=N`; trainers via `--trace-out`.

- `render_prometheus(fleet)` renders a serving fleet's state as Prometheus
  text exposition (format 0.0.4) for `GET /metrics`: lifetime counters
  (requests/sheds/errors — `ServingMetrics.totals()`, never reset, so
  scrapes are monotone), gauges (queue depth, autoscale worker count,
  breaker state), fixed-bucket latency/queue-wait/dispatch histograms,
  and reload/autoscale/promotion decision counters — all labeled by
  `model`.

`validate_prometheus_text` / `parse_prometheus_text` are the minimal
format validator and sample parser the tests and preflight's `obs` check
share, so the exposition contract is pinned by the same code in both.
"""

from __future__ import annotations

import json
import math
import os
import re
from typing import Dict, List, Optional, Tuple

from .trace import Tracer

PREFIX = "deepvision_serve_"

# ServingMetrics.totals() key -> (metric name, help) — every one a lifetime
# counter that survives snapshot(reset=True), so consecutive scrapes are
# monotone by construction
_TOTAL_COUNTERS = (
    ("requests", "requests_total", "Requests answered (batched dispatches)"),
    ("examples", "examples_total", "Examples dispatched to the device"),
    ("shed", "shed_total", "Requests shed by queue backpressure (HTTP 429)"),
    ("admission_rejected", "admission_rejected_total",
     "Requests refused at the door: deadline unmeetable (fast HTTP 503)"),
    ("deadline_expired", "deadline_expired_total",
     "Accepted requests whose deadline expired before a result (HTTP 504)"),
    ("breaker_rejected", "breaker_rejected_total",
     "Requests failed fast while the model's circuit was open (HTTP 503)"),
    ("dispatch_errors", "dispatch_errors_total",
     "Device dispatches that raised (the circuit breaker's evidence)"),
    ("observer_errors", "observer_errors_total",
     "Per-batch observer tap exceptions (counted, never silent)"),
)

_BREAKER_STATES = ("closed", "open", "half_open")
_PRECISIONS = ("bf16", "int8")


# -- Chrome trace-event export -------------------------------------------------

def chrome_trace(tracer: Tracer, since_s: Optional[float] = None) -> dict:
    """Render the tracer's ring as a Chrome trace-event JSON object."""
    spans = tracer.spans(since_s)
    pid = os.getpid()
    tids: Dict[str, int] = {}
    events: List[dict] = [
        {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
         "args": {"name": f"deepvision_tpu[{pid}]"}},
    ]

    def tid_of(name: str) -> int:
        if name not in tids:
            tids[name] = len(tids) + 1
            events.append({"ph": "M", "pid": pid, "tid": tids[name],
                           "name": "thread_name", "args": {"name": name}})
        return tids[name]

    by_id = {s["id"]: s for s in spans}
    for s in spans:
        ts_us = (s["ts"] - tracer.t0_ns) / 1000.0
        events.append({
            "name": s["name"], "cat": s["cat"], "ph": "X",
            "ts": ts_us, "dur": s["dur"] / 1000.0,
            "pid": pid, "tid": tid_of(s["tid"]),
            "args": {**s["args"], "span_id": s["id"]},
        })
        # request -> batch flow arrow: from the end of a request's
        # queue_wait span to the start of the batch span that served it
        batch = s["args"].get("batch")
        if s["name"] == "queue_wait" and batch in by_id:
            b = by_id[batch]
            events.append({"ph": "s", "id": s["id"], "cat": "flow",
                           "name": "request->batch", "pid": pid,
                           "tid": tid_of(s["tid"]),
                           "ts": ts_us + s["dur"] / 1000.0})
            events.append({"ph": "f", "bp": "e", "id": s["id"],
                           "cat": "flow", "name": "request->batch",
                           "pid": pid, "tid": tid_of(b["tid"]),
                           "ts": (b["ts"] - tracer.t0_ns) / 1000.0})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            # wall-clock anchor of monotonic ts=0, for lining the trace up
            # with serve.jsonl / train.jsonl timestamps
            "t0_unix": tracer.t0_unix,
            "spans_recorded": tracer.recorded,
            "spans_exported": len(spans),
        },
    }


def write_chrome_trace(tracer: Tracer, path: str,
                       since_s: Optional[float] = None) -> int:
    """Write the Chrome trace JSON to `path`; returns the span count."""
    trace = chrome_trace(tracer, since_s)
    with open(path, "w") as fp:
        json.dump(trace, fp)
    return trace["otherData"]["spans_exported"]


# -- Prometheus text exposition ------------------------------------------------

def _escape(v) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n",
                                                                   r"\n")


def _fmt(v) -> str:
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels(d: Dict[str, str]) -> str:
    if not d:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in d.items()) + "}"


def _emit(lines: List[str], name: str, mtype: str, help_text: str,
          samples) -> None:
    """One metric family: HELP + TYPE, then every sample grouped under it
    (the exposition format requires a family's samples to be contiguous).
    `samples` yields (suffix, labels_dict, value)."""
    lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} {mtype}")
    for suffix, labels, value in samples:
        lines.append(f"{name}{suffix}{_labels(labels)} {_fmt(value)}")


def render_prometheus(fleet) -> str:
    """Prometheus text exposition (0.0.4) of a ModelFleet's serving state,
    one `model` label per served model. Counters come from never-reset
    lifetime stores (`ServingMetrics.totals()`, reload/autoscale decision
    stats, promotion history), so consecutive scrapes are monotone."""
    models = list(fleet)
    lines: List[str] = []
    totals = {sm.name: sm.metrics.totals() for sm in models}
    for key, name, help_text in _TOTAL_COUNTERS:
        _emit(lines, PREFIX + name, "counter", help_text,
              [("", {"model": sm.name}, totals[sm.name].get(key, 0))
               for sm in models])

    _emit(lines, PREFIX + "queue_depth", "gauge",
          "Examples accepted whose results are not yet delivered",
          [("", {"model": sm.name}, sm.batcher.queue_depth)
           for sm in models])
    _emit(lines, PREFIX + "workers", "gauge",
          "Dispatcher workers in the model's pool (the autoscaler's lever)",
          [("", {"model": sm.name}, sm.batcher.workers) for sm in models])

    breaker_samples = []
    for sm in models:
        state = (sm.breaker.describe()["state"] if sm.breaker is not None
                 else None)
        for s in _BREAKER_STATES:
            breaker_samples.append(
                ("", {"model": sm.name, "state": s},
                 1 if state == s else 0))
    _emit(lines, PREFIX + "breaker_state", "gauge",
          "Circuit breaker state, one-hot over {closed, open, half_open}",
          breaker_samples)

    reload_samples = []
    autoscale_samples = []
    for sm in models:
        with sm.reload_lock:
            reload_stats = dict(sm.reload_stats)
            autoscale_stats = dict(sm.autoscale_stats)
        reload_samples += [("", {"model": sm.name, "outcome": k}, v)
                           for k, v in sorted(reload_stats.items())]
        autoscale_samples += [
            ("", {"model": sm.name, "decision": d},
             autoscale_stats.get(f"{d}s", 0))
            for d in ("scale_up", "scale_down")]
    _emit(lines, PREFIX + "reload_outcomes_total", "counter",
          "Hot weight reload outcomes (swaps, refusals, rollbacks)",
          reload_samples)
    _emit(lines, PREFIX + "autoscale_decisions_total", "counter",
          "Autoscale decisions taken by the shed-driven control loop",
          autoscale_samples)

    promo_samples = []
    for sm in models:
        if sm.promoter is None:
            continue
        counts: Dict[str, int] = {}
        for rec in list(sm.promoter.history):
            d = str(rec.get("decision", "unknown"))
            counts[d] = counts.get(d, 0) + 1
        promo_samples += [("", {"model": sm.name, "decision": d}, n)
                          for d, n in sorted(counts.items())]
    if promo_samples:
        _emit(lines, PREFIX + "promotion_decisions_total", "counter",
              "Accuracy-gated promotion decisions (shadow/canary verdicts)",
              promo_samples)

    # the flywheel axis (flywheel/controller.py): one-hot state over the
    # controller's state machine (the breaker_state pattern — an alert on
    # `flywheel_state{state="circuit_open"} == 1` is one PromQL line), the
    # monitor's latest drift evidence as gauges, and episode outcomes as a
    # labeled counter family. Conditional like promotion_decisions: only
    # models with a flywheel armed emit the families at all.
    fw_state_samples = []
    fw_shift_samples = []
    fw_decay_samples = []
    fw_outcome_samples = []
    for sm in models:
        fw = getattr(sm, "flywheel", None)
        if fw is None:
            continue
        from ..flywheel.controller import FLYWHEEL_STATES
        desc = fw.describe()
        for s in FLYWHEEL_STATES:
            fw_state_samples.append(
                ("", {"model": sm.name, "state": s},
                 1 if desc["state"] == s else 0))
        drift = desc["drift"]
        fw_shift_samples.append(
            ("", {"model": sm.name}, drift["last_input_shift"]))
        fw_decay_samples.append(
            ("", {"model": sm.name}, drift["last_watch_decay"]))
        fw_outcome_samples += [
            ("", {"model": sm.name, "outcome": k}, v)
            for k, v in sorted(desc["counters"].items())]
    if fw_state_samples:
        _emit(lines, PREFIX + "flywheel_state", "gauge",
              "Flywheel controller state, one-hot over the retrain state "
              "machine", fw_state_samples)
        _emit(lines, PREFIX + "flywheel_input_shift", "gauge",
              "Latest window's input moment shift vs the pinned reference "
              "(reference-sigma units)", fw_shift_samples)
        _emit(lines, PREFIX + "flywheel_watch_decay", "gauge",
              "Latest window's watched-metric decay vs the arm-time "
              "baseline on the pinned shard", fw_decay_samples)
        _emit(lines, PREFIX + "flywheel_episodes_total", "counter",
              "Flywheel episode outcomes (retrains, promotions, refusals, "
              "rollbacks, circuit opens)", fw_outcome_samples)

    # weight-precision provenance, one-hot over the compiled ladder: which
    # precision this model's dispatches run at (the int8 gate's outcome as
    # a scrapeable fact, not just a /healthz field)
    precision_samples = []
    for sm in models:
        active = getattr(sm.engine, "precision", "bf16")
        for p in _PRECISIONS:
            precision_samples.append(
                ("", {"model": sm.name, "precision": p},
                 1 if active == p else 0))
    _emit(lines, PREFIX + "active_precision", "gauge",
          "Active serving precision, one-hot over {bf16, int8}",
          precision_samples)

    # the mesh serving axis (docs/SERVING.md "Mesh serving"): device count
    # always (1 = single-chip engine), axis sizes per meshed model, and the
    # per-chip weight-byte accounting per compiled precision — the scrape
    # that proves a model-parallel engine actually CUT its HBM footprint
    mesh_device_samples = []
    mesh_axis_samples = []
    byte_samples = []
    for sm in models:
        axes = getattr(sm.engine, "mesh_axes", None)
        devices = 1
        if axes:
            for axis, size in axes.items():
                devices *= int(size)
                mesh_axis_samples.append(
                    ("", {"model": sm.name, "axis": axis}, size))
        mesh_device_samples.append(("", {"model": sm.name}, devices))
        if hasattr(sm.engine, "weight_bytes_per_chip"):
            for precision, nbytes in sorted(
                    sm.engine.weight_bytes_per_chip().items()):
                if nbytes is not None:
                    byte_samples.append(
                        ("", {"model": sm.name, "precision": precision},
                         nbytes))
    _emit(lines, PREFIX + "mesh_devices", "gauge",
          "Devices the engine's GSPMD programs span (1 = single chip)",
          mesh_device_samples)
    if mesh_axis_samples:
        _emit(lines, PREFIX + "mesh_axis_size", "gauge",
              "Mesh axis sizes of a mesh-sharded engine, one sample per "
              "axis", mesh_axis_samples)
    if byte_samples:
        _emit(lines, PREFIX + "weight_bytes_per_chip", "gauge",
              "Resident weight bytes on the busiest device, per compiled "
              "precision", byte_samples)

    for hist_name, help_text in (
            ("request_latency_seconds",
             "Request latency, submit to result (fixed buckets, lifetime)"),
            ("queue_wait_seconds",
             "Time from submit acceptance to dispatch start"),
            ("dispatch_seconds",
             "Device dispatch wall time per batch")):
        samples = []
        for sm in models:
            by_precision = sm.metrics.histograms_by_precision().get(
                hist_name, {})
            for precision in sorted(by_precision):
                h = by_precision[precision]
                labels = {"model": sm.name, "precision": precision}
                samples += [("_bucket", {**labels, "le": _fmt(le)}, n)
                            for le, n in h["buckets"]]
                samples.append(("_sum", dict(labels), h["sum"]))
                samples.append(("_count", dict(labels), h["count"]))
        _emit(lines, PREFIX + hist_name, "histogram", help_text, samples)
    return "\n".join(lines) + "\n"


# -- multi-replica exposition merge (the tier router's /metrics) ---------------

def merge_expositions(texts: Dict[str, str]) -> str:
    """Merge N replica expositions (`{replica_id: exposition_text}`) into
    ONE valid exposition — the tier router's `GET /metrics`
    (serve/tier.py). The merge contract:

    - counters and gauges keep one series PER REPLICA, distinguished by an
      added `replica` label — a counter stays monotone because each
      replica's series is its own lifetime store (summing across replicas
      would go BACKWARDS every time a crashed replica restarts at zero);
    - histogram families are SUMMED across replicas per label set (bucket
      counts, `_sum`, `_count`) — the fixed shared bucket edges
      (serve/metrics.LATENCY_BUCKETS_S) exist exactly so replica
      histograms aggregate; the sums stay cumulative and `+Inf == _count`
      by construction. A restart resets the sum, which is the standard
      Prometheus counter-reset semantics scrapers already handle;
    - each family's HELP/TYPE is emitted once, with every sample
      contiguous under it (the format requirement
      `validate_prometheus_text` enforces), in first-seen order.
    """
    order: List[str] = []            # family emission order (first seen)
    meta: Dict[str, Tuple[str, str]] = {}          # family -> (type, help)
    # family -> rows: histogram families aggregate into {key: value} with
    # a parallel first-seen key order; everything else appends per-replica
    hist_vals: Dict[str, Dict[tuple, float]] = {}
    hist_order: Dict[str, List[tuple]] = {}
    rows: Dict[str, List[Tuple[str, Dict[str, str], float]]] = {}

    for replica, text in texts.items():
        types: Dict[str, str] = {}
        helps: Dict[str, str] = {}
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("# HELP "):
                parts = line[len("# HELP "):].split(" ", 1)
                if parts:
                    helps[parts[0]] = parts[1] if len(parts) > 1 else ""
                continue
            if line.startswith("# TYPE "):
                parts = line[len("# TYPE "):].split()
                if len(parts) == 2:
                    types[parts[0]] = parts[1]
                continue
            if line.startswith("#"):
                continue
            m = _SAMPLE_RE.match(line)
            if m is None:
                continue
            name = m.group("name")
            fam = _family(name, types)
            if fam not in meta:
                if fam not in types:
                    continue   # sample with no TYPE: drop, never corrupt
                meta[fam] = (types[fam], helps.get(fam, ""))
                order.append(fam)
            labels = _parse_labels(m.group("labels"), [], line)
            try:
                value = _parse_value(m.group("value"))
            except ValueError:
                continue
            if meta[fam][0] in ("histogram", "summary"):
                key = (name, tuple(sorted(labels.items())))
                vals = hist_vals.setdefault(fam, {})
                if key not in vals:
                    vals[key] = 0.0
                    # first-seen order preserves ascending le within a
                    # series (every replica renders the same fixed edges)
                    hist_order.setdefault(fam, []).append((key, labels,
                                                           name))
                vals[key] += value
            else:
                rows.setdefault(fam, []).append(
                    (name, {**labels, "replica": replica}, value))

    lines: List[str] = []
    for fam in order:
        mtype, help_text = meta[fam]
        lines.append(f"# HELP {fam} {help_text}")
        lines.append(f"# TYPE {fam} {mtype}")
        if mtype in ("histogram", "summary"):
            vals = hist_vals.get(fam, {})
            for key, labels, name in hist_order.get(fam, []):
                lines.append(f"{name}{_labels(labels)}"
                             f" {_fmt(vals[key])}")
        else:
            for name, labels, value in rows.get(fam, []):
                lines.append(f"{name}{_labels(labels)} {_fmt(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


# -- minimal format validation (shared by tests + preflight) -------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"\s+(?P<value>\S+)(?:\s+(?P<ts>-?\d+))?$")
_LABEL_RE = re.compile(
    r'^(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"$')
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _parse_labels(raw: Optional[str], errors: List[str],
                  where: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    if not raw:
        return labels
    for part in raw.split(","):
        m = _LABEL_RE.match(part.strip())
        if m is None:
            errors.append(f"{where}: bad label pair {part!r}")
            continue
        labels[m.group("k")] = (m.group("v")
                                .replace(r"\"", '"')
                                .replace(r"\n", "\n")
                                .replace("\\\\", "\\"))
    return labels


def _family(name: str, types: Dict[str, str]) -> str:
    """Sample name -> declared family: histogram samples land under their
    base name's TYPE declaration."""
    for suffix in ("_bucket", "_sum", "_count"):
        base = name[:-len(suffix)] if name.endswith(suffix) else None
        if base and types.get(base) in ("histogram", "summary"):
            return base
    return name


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    return float(raw)  # raises for garbage; "NaN" parses


def parse_prometheus_text(text: str) -> Dict[Tuple[str, tuple], float]:
    """{(sample_name, sorted labels tuple): value} over every sample line —
    what the monotone-across-scrapes checks diff."""
    out: Dict[Tuple[str, tuple], float] = {}
    errors: List[str] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        labels = _parse_labels(m.group("labels"), errors, line)
        try:
            out[(m.group("name"), tuple(sorted(labels.items())))] = \
                _parse_value(m.group("value"))
        except ValueError:
            continue
    return out


def validate_prometheus_text(text: str) -> List[str]:
    """Minimal Prometheus text-format (0.0.4) validation; returns a list of
    problems (empty = valid). Checks: metric-name/label charset, every
    sample preceded by its family's TYPE (with a HELP), declared types
    legal, histogram buckets cumulative with an le="+Inf" bucket equal to
    `_count`."""
    errors: List[str] = []
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    # (family, non-le labels) -> [(le_value, count)], plus _count samples
    hist_buckets: Dict[tuple, List[Tuple[float, float]]] = {}
    hist_counts: Dict[tuple, float] = {}

    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(" ", 1)
            if not parts or not _NAME_RE.match(parts[0]):
                errors.append(f"line {i}: bad HELP metric name")
            else:
                helps[parts[0]] = parts[1] if len(parts) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split()
            if len(parts) != 2 or not _NAME_RE.match(parts[0]):
                errors.append(f"line {i}: malformed TYPE line {line!r}")
                continue
            name, mtype = parts
            if mtype not in _TYPES:
                errors.append(f"line {i}: unknown type {mtype!r}")
            if name in types:
                errors.append(f"line {i}: duplicate TYPE for {name}")
            types[name] = mtype
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line.strip())
        if m is None:
            errors.append(f"line {i}: unparseable sample {line!r}")
            continue
        name = m.group("name")
        if not _NAME_RE.match(name):
            errors.append(f"line {i}: bad metric name {name!r}")
            continue
        labels = _parse_labels(m.group("labels"), errors, f"line {i}")
        try:
            value = _parse_value(m.group("value"))
        except ValueError:
            errors.append(f"line {i}: bad sample value {m.group('value')!r}")
            continue
        fam = _family(name, types)
        if fam not in types:
            errors.append(f"line {i}: sample {name} has no preceding TYPE")
        elif fam not in helps:
            errors.append(f"line {i}: family {fam} has no HELP line")
        if name.endswith("_bucket") and types.get(fam) == "histogram":
            if "le" not in labels:
                errors.append(f"line {i}: histogram bucket without le label")
                continue
            key = (fam, tuple(sorted((k, v) for k, v in labels.items()
                                     if k != "le")))
            try:
                hist_buckets.setdefault(key, []).append(
                    (_parse_value(labels["le"]), value))
            except ValueError:
                errors.append(f"line {i}: bad le value {labels['le']!r}")
        elif name.endswith("_count") and types.get(fam) == "histogram":
            hist_counts[(fam, tuple(sorted(labels.items())))] = value

    for (fam, labels), buckets in hist_buckets.items():
        les = [le for le, _ in buckets]
        counts = [n for _, n in buckets]
        if les != sorted(les):
            errors.append(f"{fam}{dict(labels)}: bucket le values not "
                          f"ascending")
        if any(b > a for b, a in zip(counts, counts[1:])):
            errors.append(f"{fam}{dict(labels)}: bucket counts not "
                          f"cumulative")
        if not les or not math.isinf(les[-1]):
            errors.append(f"{fam}{dict(labels)}: missing le=\"+Inf\" bucket")
        else:
            total = hist_counts.get((fam, labels))
            if total is not None and counts[-1] != total:
                errors.append(f"{fam}{dict(labels)}: +Inf bucket "
                              f"{counts[-1]} != _count {total}")
    return errors


# the serve-exposition labeling contract layered ON TOP of the format
# rules: every dispatch/latency histogram series must carry BOTH the model
# and the precision label (the int8 serving axis — a scrape that loses the
# precision split would average a precision flip away), and the
# active-precision one-hot gauge must be present for every served model.
_PRECISION_LABELED = ("deepvision_serve_request_latency_seconds",
                      "deepvision_serve_queue_wait_seconds",
                      "deepvision_serve_dispatch_seconds")

# mesh-serving gauges (the GSPMD predict axis) and their required labels:
# per-chip weight bytes must keep the precision split (averaging bf16 and
# int8 per-chip bytes would hide exactly the win int8-on-a-mesh buys), and
# axis-size samples are meaningless without naming WHICH axis
_MESH_LABELED = {"deepvision_serve_weight_bytes_per_chip":
                 ("model", "precision"),
                 "deepvision_serve_mesh_axis_size": ("model", "axis"),
                 "deepvision_serve_mesh_devices": ("model",),
                 # the flywheel's one-hot state gauge rides the same
                 # required-labels contract: a state sample without the
                 # state label cannot be alerted on
                 "deepvision_serve_flywheel_state": ("model", "state"),
                 "deepvision_serve_flywheel_episodes_total":
                 ("model", "outcome")}


def validate_serve_exposition(text: str) -> List[str]:
    """Format validation (`validate_prometheus_text`) PLUS the serving
    fleet's own labeling contract: model+precision labels on every
    dispatch/latency histogram sample, precision values from the compiled
    ladder, the `active_precision` gauge family present, and the mesh
    gauges (`mesh_devices`, `mesh_axis_size`, `weight_bytes_per_chip`)
    carrying their model/axis/precision labels. The shared validator
    preflight's `obs`/`quant` checks and tests/test_obs.py run against
    GET /metrics."""
    errors = validate_prometheus_text(text)
    saw_active = False
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name = m.group("name")
        if name.startswith("deepvision_serve_active_precision"):
            saw_active = True
        if name in _MESH_LABELED:
            labels = _parse_labels(m.group("labels"), errors, line)
            for required in _MESH_LABELED[name]:
                if required not in labels:
                    errors.append(f"{name}: mesh gauge sample missing the "
                                  f"{required!r} label")
            if ("precision" in _MESH_LABELED[name]
                    and labels.get("precision") not in (None, *_PRECISIONS)):
                errors.append(f"{name}: unknown precision label "
                              f"{labels.get('precision')!r}")
            continue
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[:-len(suffix)]
                break
        if base not in _PRECISION_LABELED:
            continue
        labels = _parse_labels(m.group("labels"), errors, line)
        for required in ("model", "precision"):
            if required not in labels:
                errors.append(f"{name}: histogram sample missing the "
                              f"{required!r} label")
        if labels.get("precision") not in (None, *_PRECISIONS):
            errors.append(f"{name}: unknown precision label "
                          f"{labels.get('precision')!r}")
    if "deepvision_serve_requests_total" in text and not saw_active:
        errors.append("serve exposition lacks the "
                      "deepvision_serve_active_precision gauge")
    return errors
