"""Observability: end-to-end tracing + metrics exposition (zero deps).

The serving stack can shed, autoscale, canary, and roll back — but until
now every one of those decisions was explained by scattered surfaces
(`/stats` JSON, `resilience_*` events, stderr lines). This package is the
instrument that turns them into one joined picture:

- `obs.trace` — a thread-safe, ring-buffered, sampled span recorder with
  request-id context propagation. One branch when disabled; a few dict
  builds per sampled request when enabled.
- `obs.export` — Chrome trace-event JSON (loadable in Perfetto /
  `chrome://tracing`) and Prometheus text exposition (`GET /metrics`),
  plus the minimal format validator the tests and preflight share.

See docs/OBSERVABILITY.md for the span taxonomy, the scrape quickstart,
and the correlation contract joining spans to `resilience_*` events.
"""

from .trace import Tracer, TraceContext, new_request_id  # noqa: F401
