"""Span tracing: follow one request (or one training step) through the stack.

A `Tracer` records completed spans — `(name, category, start_ns, dur_ns,
thread, args)` dicts — into a bounded ring buffer under one lock. Nothing
is written to disk on the hot path; export (obs/export.py) walks the ring
on demand (`GET /trace` on the serve server, `--trace-out` on trainers).

Cost contract (the tentpole's pin):

- DISABLED tracing is one attribute check per call site: every producer
  guards with ``tr is not None and tr.enabled`` (or calls
  `request_context`, which returns None immediately), so the steady-state
  serving and training hot paths pay a single branch.
- ENABLED tracing is SAMPLED per request: `request_context` hands out a
  `TraceContext` for 1-in-N requests (`sample`, default
  DEEPVISION_TRACE_SAMPLE=0.1) and None for the rest — an unsampled
  request records zero spans. A client-supplied `X-Request-Id` header
  forces sampling (`forced=True`): an operator tracing one specific
  request must always get its spans. Batch-level spans (one per device
  dispatch, ~1-2 orders of magnitude rarer than requests) are recorded
  whenever tracing is enabled, so bucket/generation/worker coverage is
  continuous even at low sample rates.

Context propagation: every HTTP request gets a `request_id` (client
`X-Request-Id` or `new_request_id()`), echoed in every response —
including 503/504 sheds — and stamped into each of its spans' args, so
the span chain (http_request → admission → queue_wait → batch →
device_dispatch → response_write) and any `resilience_*` event the
request triggered (core/resilience.log_resilience_event's
`request_id`/`trace_ref` fields) join on one key.

Clock: `time.monotonic_ns()` — the same CLOCK_MONOTONIC the batcher's
`time.monotonic()` timestamps use, so span starts can be derived from
existing request timestamps without extra clock reads.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Optional

# default per-request sampling rate: 1-in-10 requests fully traced. At the
# load bench's ~4k req/s this is ~400 sampled requests/s x ~4 spans — well
# under the 3% overhead bar the bench asserts (bench_serve.py --trace-out).
DEFAULT_SAMPLE = float(os.environ.get("DEEPVISION_TRACE_SAMPLE", "0.1"))

_RID_SEQ = itertools.count(1)


def new_request_id() -> str:
    """Process-unique request id for requests that didn't bring their own
    `X-Request-Id`: short enough to read in a log line, unique enough to
    join across serve.jsonl, /trace, and a client's own records."""
    return f"r{next(_RID_SEQ)}-{uuid.uuid4().hex[:8]}"


class TraceContext:
    """A sampled request's trace handle, threaded submit→dispatch→response.

    `root_id` is allocated at sampling time (before the root span is
    recorded) so refusal paths can stamp a stable `trace_ref`
    (``span:<root_id>``) into the resilience event they log even though
    the http_request span itself is only recorded when the response goes
    out."""

    __slots__ = ("tracer", "request_id", "root_id")

    def __init__(self, tracer: "Tracer", request_id: str, root_id: int):
        self.tracer = tracer
        self.request_id = request_id
        self.root_id = root_id

    @property
    def trace_ref(self) -> str:
        return f"span:{self.root_id}"


class Tracer:
    """Thread-safe ring-buffered span recorder.

    `capacity` bounds memory (oldest spans fall off — /trace?secs=N is a
    recent-history window by design); `sample` is the per-request
    sampling rate (see module docstring); `enabled=False` turns every
    entry point into a cheap no-op so a single constructor flag is the
    whole kill switch."""

    def __init__(self, capacity: int = 16384, sample: Optional[float] = None,
                 enabled: bool = True):
        self.enabled = bool(enabled)
        self.sample = DEFAULT_SAMPLE if sample is None else float(sample)
        if not 0.0 <= self.sample <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {self.sample}")
        # deterministic 1-in-N sampling (counter, not RNG): reproducible in
        # tests, and the rate is exact rather than merely expected
        self._every = (int(round(1.0 / self.sample)) if self.sample > 0
                       else 0)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._req_count = itertools.count(0)
        self.recorded = 0          # lifetime spans recorded (ring may drop)
        # export anchors: monotonic origin + the wall-clock instant it maps
        # to, so exported traces can be lined up with JSONL timestamps
        self.t0_ns = time.monotonic_ns()
        self.t0_unix = time.time()

    # -- context -----------------------------------------------------------

    def new_id(self) -> int:
        return next(self._ids)

    def request_context(self, request_id: Optional[str] = None, *,
                        forced: bool = False) -> Optional[TraceContext]:
        """Sampling decision for one request: a `TraceContext` when this
        request's spans should be recorded, None otherwise (disabled
        tracer, or not this request's turn). `forced=True` (client
        brought an explicit X-Request-Id) always samples."""
        if not self.enabled:
            return None
        if not forced:
            if self._every == 0:
                return None
            if next(self._req_count) % self._every != 0:
                return None
        return TraceContext(self, request_id or new_request_id(),
                            self.new_id())

    # -- recording ---------------------------------------------------------

    def add(self, name: str, cat: str, start_ns: int, dur_ns: int, *,
            args: Optional[dict] = None, span_id: Optional[int] = None,
            tid: Optional[str] = None) -> int:
        """Record one completed span; returns its id (for linkage args).
        A disabled tracer records nothing and returns 0."""
        if not self.enabled:
            return 0
        sid = span_id if span_id is not None else self.new_id()
        span = {"id": sid, "name": name, "cat": cat,
                "ts": int(start_ns), "dur": max(0, int(dur_ns)),
                "tid": tid or threading.current_thread().name,
                "args": args or {}}
        with self._lock:
            self._spans.append(span)
            self.recorded += 1
        return sid

    @contextmanager
    def span(self, name: str, cat: str = "serve", **args):
        """Record the wrapped block as one span; yields a mutable args dict
        (extra tags set inside the block land on the span)."""
        if not self.enabled:
            yield args
            return
        t0 = time.monotonic_ns()
        try:
            yield args
        finally:
            self.add(name, cat, t0, time.monotonic_ns() - t0, args=args)

    # -- reading -----------------------------------------------------------

    def spans(self, since_s: Optional[float] = None) -> list:
        """Snapshot of the ring, oldest first; `since_s` keeps only spans
        that ENDED within the last `since_s` seconds."""
        with self._lock:
            items = list(self._spans)
        if since_s is not None:
            cutoff = time.monotonic_ns() - int(since_s * 1e9)
            items = [s for s in items if s["ts"] + s["dur"] >= cutoff]
        return items

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
