#!/usr/bin/env python
"""Train LeNet models on TPU — `python train.py -m <model> [-c latest] [--synthetic]`.

Per-family entrypoint matching the reference's UX (LeNet/pytorch|tensorflow/train.py),
backed by the shared deepvision_tpu Trainer instead of a copy-pasted loop.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from deepvision_tpu.cli import run_classification

MODELS = ["lenet5", "lenet5_digits"]

if __name__ == "__main__":
    run_classification("LeNet", MODELS)
