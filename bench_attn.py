"""Benchmark: fused (Pallas flash) vs naive attention — bytes, parity, serving.

Prints ONE JSON line in bench.py's schema ({"metric", "value", "unit",
"vs_baseline", ...}). `value` is the HBM-bytes cut of the fused lowering at
the ViT working point the kernel is tiled for (B=8, H=6, N=196, D=64, bf16 —
a 224px/16px-patch ViT-Small's attention op), measured on the jaxvet
walker's fusion-blind bytes proxy (check/jaxpr_walk.cost_summary): the naive
lowering is charged every equation's operands and results — including both
(N, N) HBM materializations of the score matrix — while the pallas_call is
charged exactly its per-program block DMAs. `vs_baseline` divides the cut by
the 2x bar.

Hard gates (exit 1 on violation — the kernel's correctness and serving
contract, not throughput bars):

- bytes cut >= 2x at the seq-196 working point (the kernel's reason to
  exist: the (N, N) softmax chain never reaches HBM);
- fused-vs-naive parity <= 2e-2 at bf16 and <= 2e-5 at f32 on identical
  inputs (docs/ATTENTION.md derives why bf16 parity is a one-rounding
  story: both paths accumulate in f32, naive rounds its scores once);
- zero recompiles across a stage -> predict -> promote cycle on a ViT
  engine with the fused kernel armed (interpret mode — the same kernel
  jaxpr the TPU path compiles) — promotion must reuse every AOT bucket.

steps/sec rides along HONESTLY: on CPU the fused kernel runs under the
Pallas interpreter, whose unrolled per-program bodies are far slower than
the naive XLA fusion, so `steps_per_sec.fused / steps_per_sec.naive` is
WELL BELOW 1 here. That mirrors docs/TUNING.md item 8's dispatch-axis
lesson inverted: the fused win is proportional to what the fusion removes
(HBM round-trips of the (N, N) matrix), i.e. it lands exactly in the
bandwidth-bound TPU regime the bytes proxy models — judge wall-clock on a
real chip, judge bytes here.

    python bench_attn.py                  # one JSON line
    python bench_attn.py --batch 4 --heads 6 --seq 196 --head-dim 64
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BYTES_CUT_BAR = 2.0        # fused must at least halve the naive bytes proxy
PARITY_BF16 = 2e-2         # one extra rounding of the naive scores (bf16)
PARITY_F32 = 2e-5          # reassociation-only error (f32)


def _bytes_proxy(b, h, n, d, dtype):
    """Walker-proxy cost rows for the attention op alone, both lowerings."""
    import jax

    from deepvision_tpu.check.jaxpr_walk import cost_summary
    from deepvision_tpu.ops.attention import attention

    def jitted(impl):
        return jax.jit(lambda q, k, v: attention(q, k, v, impl=impl))

    sds = jax.ShapeDtypeStruct((b, h, n, d), dtype)
    return {name: cost_summary(jitted(impl).trace(sds, sds, sds).jaxpr)
            for name, impl in (("naive", "naive"), ("fused", "interpret"))}


def _parity_and_speed(b, h, n, d, timed_calls):
    """Max-abs parity at f32 and bf16 plus compiled calls/sec per lowering
    (fused runs under the interpreter on CPU — see the module docstring for
    why that wall-clock number is reported but not gated)."""
    import jax
    import jax.numpy as jnp

    from deepvision_tpu.ops.attention import attention

    def jitted(impl):
        return jax.jit(lambda q, k, v: attention(q, k, v, impl=impl))

    # jits hoisted out of the dtype/timing loops (factory pattern): one
    # compiled callable per lowering, retraced only per input dtype
    fns = {"naive": jitted("naive"), "interpret": jitted("interpret")}
    parity = {}
    speed = {}
    for dtype, bound_name in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
        ks = jax.random.split(jax.random.PRNGKey(n + d), 3)
        q, k, v = (jax.random.normal(kk, (b, h, n, d), dtype) for kk in ks)
        outs = {}
        for impl, fn in fns.items():
            out = jax.block_until_ready(fn(q, k, v))
            outs[impl] = out.astype(jnp.float32)
            if dtype == jnp.bfloat16:      # time the serving dtype only
                t0 = time.perf_counter()
                for _ in range(timed_calls):
                    out = fn(q, k, v)
                jax.block_until_ready(out)
                key = "fused" if impl == "interpret" else impl
                speed[key] = timed_calls / (time.perf_counter() - t0)
        parity[bound_name] = float(
            jnp.max(jnp.abs(outs["naive"] - outs["interpret"])))
    return parity, speed


def _promotion_recompiles():
    """stage -> predict(candidate) -> promote -> predict on a ViT engine
    with the fused kernel armed; returns (programs compiled at startup,
    programs compiled after the cycle) — equal means zero recompiles."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.train_state import init_model
    from deepvision_tpu.core.trainer import build_model_from_config
    from deepvision_tpu.serve.engine import PredictEngine

    cfg = get_config("vit_tiny")
    # "interpret" arms the SAME fused kernel the TPU path compiles, under
    # the Pallas interpreter — the engine's AOT buckets carry pallas_call
    cfg = cfg.replace(model_kwargs={**cfg.model_kwargs,
                                    "attention_impl": "interpret"})
    model, cfg = build_model_from_config(cfg)
    sz, ch = cfg.data.image_size, cfg.data.channels
    params, batch_stats = init_model(model, jax.random.PRNGKey(cfg.seed),
                                     jnp.zeros((2, sz, sz, ch), jnp.float32))
    variables = {"params": params}
    if jax.tree_util.tree_leaves(batch_stats):
        variables["batch_stats"] = batch_stats
    engine = PredictEngine(model.apply, variables,
                           example_shape=(sz, sz, ch), buckets=(1, 8),
                           compute_dtype=jnp.dtype(cfg.dtype),
                           take_first_output=True, name=cfg.name,
                           verbose=False)
    n_startup = len(engine.compile_log)
    x = np.random.RandomState(0).randn(2, sz, sz, ch).astype(np.float32)
    live_out = engine.predict(x)
    cand = jax.tree_util.tree_map(lambda a: np.asarray(a) * 1.01,
                                  jax.device_get(engine._variables))
    engine.stage_candidate(cand, {"verified": True})
    engine.predict(x, generation="candidate")
    engine.promote_candidate()
    promoted_out = engine.predict(x)
    assert not np.allclose(live_out, promoted_out)  # new weights really live
    return n_startup, len(engine.compile_log)


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--heads", type=int, default=6)
    p.add_argument("--seq", type=int, default=196,
                   help="sequence length of the bytes/parity working point")
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--timed-calls", type=int, default=5)
    args = p.parse_args(argv)

    # bandwidth-model measurement: never implicitly claim a relayed TPU
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    from deepvision_tpu.cli import setup_compilation_cache
    setup_compilation_cache()
    platform = jax.devices()[0].platform

    b, h, n, d = args.batch, args.heads, args.seq, args.head_dim
    rows = _bytes_proxy(b, h, n, d, jnp.bfloat16)
    cut = rows["naive"]["bytes"] / rows["fused"]["bytes"]
    parity, speed = _parity_and_speed(b, h, n, d, args.timed_calls)
    n_startup, n_after = _promotion_recompiles()

    failures = []
    if cut < BYTES_CUT_BAR:
        failures.append(f"bytes cut {cut:.2f}x below the {BYTES_CUT_BAR}x "
                        f"bar at seq {n}")
    if parity["bf16"] > PARITY_BF16:
        failures.append(f"bf16 parity {parity['bf16']:.3e} exceeds "
                        f"{PARITY_BF16:.0e}")
    if parity["f32"] > PARITY_F32:
        failures.append(f"f32 parity {parity['f32']:.3e} exceeds "
                        f"{PARITY_F32:.0e}")
    if n_after != n_startup:
        failures.append(f"promotion with fused armed compiled "
                        f"{n_after - n_startup} new programs (want 0)")

    print(json.dumps({
        "metric": f"fused_attention_bytes_cut"
                  f"(b{b},h{h},n{n},d{d},bf16,walker_proxy,{platform})",
        "value": round(cut, 3),
        "unit": "x_vs_naive",
        "vs_baseline": round(cut / BYTES_CUT_BAR, 3),
        "platform": platform,
        "bytes_per_step": {"naive": rows["naive"]["bytes"],
                           "fused": rows["fused"]["bytes"]},
        "flops_per_step": {"naive": rows["naive"]["flops"],
                           "fused": rows["fused"]["flops"]},
        "parity_max_abs_err": {k: round(v, 8) for k, v in parity.items()},
        # honest CPU wall-clock: interpreter-mode fused vs XLA naive.
        # The regime note is the point (docs/TUNING.md item 8's lesson,
        # attention edition): this ratio inverts on hardware whose HBM
        # round-trips the fusion actually removes.
        "attn_calls_per_sec": {k: round(v, 2) for k, v in speed.items()},
        "cpu_regime_note": "fused runs under the Pallas interpreter on "
                           "CPU; judge wall-clock on a real chip, judge "
                           "bytes here",
        "promotion_programs": {"startup": n_startup, "after_cycle": n_after},
        "timed_calls": args.timed_calls,
    }))
    if failures:
        for f in failures:
            print(f"bench_attn: FAIL {f}", file=sys.stderr, flush=True)
        raise SystemExit(1)


if __name__ == "__main__":
    main()
