"""Serving benchmark: dynamic micro-batched dispatch vs per-request dispatch.

Prints ONE JSON line in bench.py's schema ({"metric", "value", "unit",
"vs_baseline", ...}). `value` is the dynamic batcher's sustained images/sec
under closed-loop synthetic offered load (single-image requests — the
serving worst case the tentpole targets).

`--load` switches to the OPEN-LOOP fleet bench (docs/SERVING.md "Load
bench"): a sustained-QPS arrival schedule — requests fire on the clock,
never gated on completions — over a >=2-model fleet, reporting sustained
QPS, p99-under-load, and shed rate. `--load --trace-out trace.json` runs
that schedule twice (untraced, then traced at default sampling), dumps
the traced run's Perfetto trace, and asserts tracing kept sustained QPS
within 3% of the untraced run (docs/OBSERVABILITY.md). `--load --spike` benches the
TRANSIENT instead of steady state: offered QPS steps 1x -> 3x -> 1x while
the shed-driven autoscaler (serve/autoscale.py) scales each model's
dispatcher pool, reporting time-to-absorb (seconds from spike onset until
the windowed shed rate returns under 1%), shed during the transient, p99
per phase, and the zero-recompile proof (compile logs unchanged, jit
caches empty) — worker spawn is a thread + a reference to the shared AOT
bucket cache. On a multi-core host the extra workers restore capacity
mid-spike; on a 1-core host they buy collect/dispatch overlap and the
absorb completes as the backlog drains after the step back down — the
report states workers and phase p99s so either reading is honest. `--load --promote-at <sec>` layers the
accuracy-gated promotion cycle (docs/SERVING.md "Promotion") on top: a new
checkpoint epoch is committed mid-bench and runs the full
shadow -> gate -> canary -> promote pipeline while the arrival schedule
keeps firing, reporting `promotion_secs`, shed rate, and the p99 delta
through the swap — plus the zero-failed / zero-mixed-generation response
audit. Arm `DEEPVISION_FAULT_PROMOTE_REGRESS=2:<accuracy|latency>` and the
same bench proves the auto-rollback: the cycle retreats to the incumbent
and the decision lands on the resilience_ stream. Closed-loop load (the default mode's
clients) measures capacity but hides overload: a saturated server slows
its own clients down, so offered load politely collapses to whatever the
server can do. Open-loop arrivals are what real traffic does — they keep
coming — so p99 and shed rate under a FIXED offered rate are the numbers a
capacity plan can actually use (Schroeder et al., "Open Versus Closed").

`--mesh` benches the mesh-sharded (GSPMD) predict path (docs/SERVING.md
"Mesh serving") instead: the SAME model built twice — once single-chip,
once over a `data x model` serve mesh (CPU virtual devices: run under
`XLA_FLAGS=--xla_force_host_platform_device_count=8`, which `make
bench-serve-mesh` pins) — reporting per-chip resident weight bytes (the
headline: the bar is a cut >= 0.98x the model-axis size vs the
single-chip engine), p99 at the max-batch bucket for both engines, the
largest registered config servable under a per-chip HBM budget each way
(analytic, `jax.eval_shape` — no weights materialized), and the
zero-recompile proof ACROSS A PROMOTION: a candidate generation is
staged, shadow-dispatched, and promoted on the mesh engine with the
compile log unchanged and the jit fallback cache empty.

`--flywheel` benches the serve->train->serve flywheel (flywheel/,
docs/FAILURES.md "Flywheel decisions") instead: the deterministic
drift-shift fault moves the live input distribution from the first
reservoir window, closed-loop clients keep firing, and the bench drives
the drift monitor tick-by-tick — reporting time-to-detect (monitor armed
-> hysteresis streak confirmed), time-to-promoted (confirmed -> the
fine-tuned epoch live through the shadow/canary gate) as the headline
`value`, and goodput during the episode over steady state as
`vs_baseline`. Hard bars: zero failed responses, zero shed, zero
serve-path recompiles, decision == promoted — the loop that answers
drift with a gated retrain must not cost healthy traffic anything but
shared CPU.

`--tier` benches the multi-replica tier (serve/tier.py, docs/SERVING.md
"Replica tier") instead: warm-vs-cold replica boot-to-first-200 through the
tier's shared persistent XLA compile cache (bars: warm >=2x faster, zero
warm-path recompiles), then a kill-one-of-3 spike — SIGKILL lands on a
supervised replica mid-schedule and the bars are zero failed client
responses after the ejection window, post-kill goodput within 5% of
pre-kill, and supervised readmission of the victim.

Two baselines, measured in the same process on the same model/config:

- `vs_baseline` compares against the NAIVE per-request loop the serving
  stack replaces — the status quo the tentpole motivation names: "per-
  request dispatch, per-shape retrace, and batch-of-1 utilization", i.e. a
  fresh `jax.jit(predict)(...)` per call (the exact pattern jaxlint's
  JIT001 rule exists to catch). The acceptance bar is vs_baseline >= 5.
- `vs_compiled_b1` is the STRICT bound: against sequential batch-of-1
  dispatch of the engine's own AOT-compiled bucket-1 program (no retrace,
  no python waste — the best possible unbatched loop). This ratio is what
  device-side batching alone buys: bounded by batch-compute sublinearity,
  so ~1.3x on a single-core CPU host (batch compute is linear there,
  `cpu_cores` says so), >=5x once cores/MXU parallelism make batch-32
  sublinear, and largest on relay-attached TPUs where per-dispatch latency
  dominates (docs/TUNING.md "How to time through a tunneled TPU").

Latency is reported from a separate phase at ~20% of measured capacity:
closed-loop saturation measures queue depth, not serving latency, so the
p99 contract (p99 <= max_delay_ms + one max-bucket compute time,
docs/SERVING.md) is checked at an overload-free operating point and
reported as `latency_ok`.

Deliberately CPU-safe (small default model, synthetic load, bucket compiles
against the persistent XLA cache — `compile_cache` in the record says
whether this run re-paid them). Knobs: DEEPVISION_SERVE_BENCH_MODEL,
DEEPVISION_SERVE_BENCH_SECS (per phase), DEEPVISION_SERVE_BENCH_MAX_BATCH,
DEEPVISION_SERVE_BENCH_DELAY_MS.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

import numpy as np

# deadline-bounded result waits everywhere (serve/batcher.result_within):
# a wedged model fails the bench in seconds with DeadlineExpired instead
# of blocking a blind 120 s per future
BENCH_WAIT_S = float(os.environ.get("DEEPVISION_SERVE_BENCH_WAIT_S", "30"))


def closed_loop() -> None:
    model_name = os.environ.get("DEEPVISION_SERVE_BENCH_MODEL", "lenet5")
    secs = float(os.environ.get("DEEPVISION_SERVE_BENCH_SECS", "2.0"))
    max_delay_ms = float(os.environ.get("DEEPVISION_SERVE_BENCH_DELAY_MS",
                                        "5.0"))
    max_batch = int(os.environ.get("DEEPVISION_SERVE_BENCH_MAX_BATCH", "32"))

    import jax

    from deepvision_tpu.cli import (compilation_cache_stats,
                                    setup_compilation_cache)
    setup_compilation_cache()

    from deepvision_tpu.serve.batcher import (DynamicBatcher,
                                              RequestRejected,
                                              result_within)
    from deepvision_tpu.serve.engine import PredictEngine
    from deepvision_tpu.serve.metrics import ServingMetrics

    engine = PredictEngine.from_config(
        model_name, buckets=(1, 8, 32), max_batch=max_batch)
    engine.warmup()
    batch_ms = engine.measure_batch_ms(max_batch)
    platform = jax.devices()[0].platform
    x1 = np.random.RandomState(0).randn(
        1, *engine.example_shape).astype(engine.input_dtype)

    # -- baseline A: the naive loop (dispatch + retrace + batch-of-1) ------
    # a fresh jitted callable per predict call retraces every time — the
    # JIT001 anti-pattern, here ON PURPOSE as the measured status quo
    predict_fn = engine._predict_fn
    t0 = time.perf_counter()
    n_naive = 0
    while time.perf_counter() - t0 < min(secs, 2.0) and n_naive < 100:
        # jaxlint: disable=JIT001 — this IS the measured anti-pattern
        np.asarray(jax.jit(predict_fn)(engine._variables, x1)[:1])
        n_naive += 1
    naive_ips = n_naive / (time.perf_counter() - t0)

    # -- baseline B: strict sequential batch-of-1 over the AOT cache -------
    t0 = time.perf_counter()
    n_seq = 0
    while time.perf_counter() - t0 < secs:
        engine.predict(x1)
        n_seq += 1
    seq_ips = n_seq / (time.perf_counter() - t0)

    # -- dynamic batcher: closed-loop saturation ---------------------------
    metrics = ServingMetrics(window=8192)
    batcher = DynamicBatcher(engine, max_delay_ms=max_delay_ms,
                             max_queue_examples=64 * max_batch,
                             metrics=metrics)
    stop = threading.Event()

    def client(i: int) -> None:
        xi = np.random.RandomState(i).randn(
            1, *engine.example_shape).astype(engine.input_dtype)
        while not stop.is_set():
            try:
                result_within(batcher.submit(xi), BENCH_WAIT_S,
                              what="bench request")
            except RequestRejected:
                time.sleep(0.001)

    n_clients = min(128, 3 * max_batch)
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n_clients)]
    for t in threads:
        t.start()
    time.sleep(0.25)                 # fill the pipeline before timing
    metrics.snapshot(reset=True)
    time.sleep(secs)
    thr = metrics.snapshot(reset=True)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    dyn_ips = thr["images_per_sec"]

    # -- latency at ~20% capacity (overload-free operating point) ----------
    metrics.snapshot(reset=True)     # discard the client wind-down tail
    rate = max(50.0, 0.2 * dyn_ips)  # requests/sec offered
    tick = 0.002
    per_tick = max(1, int(rate * tick))
    futs = []
    shed = 0
    end = time.perf_counter() + secs
    while time.perf_counter() < end:
        for _ in range(per_tick):
            try:
                futs.append(batcher.submit(x1))
            except RequestRejected:
                shed += 1
        time.sleep(tick)
    for f in futs:
        result_within(f, BENCH_WAIT_S, what="bench request")
    lat = metrics.snapshot()
    batcher.drain(timeout=30)

    p99 = lat.get("p99_ms", float("inf"))
    bound = max_delay_ms + batch_ms
    print(json.dumps({
        "metric": f"serve_dynamic_batch_images_per_sec(1img/req,"
                  f"{model_name},b{max_batch},delay{max_delay_ms:g}ms,"
                  f"{platform})",
        "value": round(dyn_ips, 2),
        "unit": "images/sec",
        # vs the naive per-request loop (dispatch+retrace+batch-of-1); the
        # tentpole acceptance bar is >= 5
        "vs_baseline": round(dyn_ips / naive_ips, 3) if naive_ips else 0.0,
        "baseline_naive_images_per_sec": round(naive_ips, 2),
        "baseline_naive": "fresh jax.jit(predict)(...) per request "
                          "(per-request dispatch + per-shape retrace + "
                          "batch-of-1; the JIT001 pattern)",
        # strict bound: sequential batch-of-1 over the same AOT cache
        "vs_compiled_b1": round(dyn_ips / seq_ips, 3) if seq_ips else 0.0,
        "sequential_compiled_b1_images_per_sec": round(seq_ips, 2),
        "batch_compute_ms": round(batch_ms, 3),
        "max_delay_ms": max_delay_ms,
        "p50_ms": round(lat.get("p50_ms", 0.0), 3),
        "p99_ms": round(p99, 3),
        "p99_bound_ms": round(bound, 3),
        "latency_ok": bool(p99 <= bound),
        "latency_phase_offered_per_sec": round(rate, 1),
        "shed_requests": shed,
        "padding_waste": round(thr.get("padding_waste", 0.0), 4),
        "mean_batch_fill": round(thr.get("mean_batch_fill", 0.0), 2),
        "cpu_cores": os.cpu_count(),
        "platform": platform,
        "compile_cache": compilation_cache_stats(),
    }))


def open_loop(args) -> None:
    """Open-loop fleet load bench: arrivals on a fixed sustained-QPS
    schedule round-robined over the fleet's models, single-image requests
    (the worst case). Submissions never wait for completions; when a
    model's queue is full the request is SHED (counted, not retried) —
    exactly what the HTTP front door does with 429.

    `--trace-out PATH` runs the SAME schedule twice — once untraced, once
    with span tracing attached at default sampling — writes the traced
    run's Perfetto/Chrome trace to PATH, and asserts the tracing overhead
    kept sustained QPS within 3% of the untraced run (the obs tentpole's
    hot-path pin, docs/OBSERVABILITY.md)."""
    import jax

    from deepvision_tpu.cli import (compilation_cache_stats,
                                    setup_compilation_cache)
    setup_compilation_cache()

    from deepvision_tpu.serve.batcher import (RequestRejected,
                                              result_within)
    from deepvision_tpu.serve.engine import PredictEngine
    from deepvision_tpu.serve.fleet import ModelFleet

    names = [s.strip() for s in args.models.split(",") if s.strip()]
    max_batch = args.max_batch
    fleet = ModelFleet()
    for name in names:
        engine = PredictEngine.from_config(
            name, buckets=(1, 8, 32), max_batch=max_batch, verbose=False)
        engine.warmup()
        fleet.add(engine, max_delay_ms=args.delay_ms,
                  max_queue_examples=8 * max_batch)
    models = list(fleet)
    platform = jax.devices()[0].platform

    # capacity estimate for the auto offered rate: the fleet shares ONE
    # device, so a fair round-robin of one max-bucket dispatch per model
    # yields sum(max_batch) images per sum(batch_ms) — NOT the sum of each
    # model's solo capacity
    batch_ms = {sm.name: sm.engine.measure_batch_ms(max_batch)
                for sm in models}
    fleet_capacity = (max_batch * len(models)
                      / (sum(batch_ms.values()) / 1000.0))
    offered_qps = args.qps or round(0.7 * fleet_capacity, 1)

    xs = {sm.name: np.random.RandomState(1).randn(
        1, *sm.engine.example_shape).astype(sm.engine.input_dtype)
        for sm in models}

    def run_schedule(tracer=None, qps=None):
        """One pass of the arrival schedule: request i fires at t0 + i/qps,
        whether or not any earlier request has completed — the generator
        only sleeps until the next arrival time, it never blocks on a
        future. Returns (sustained_qps, under_load, final, offered)."""
        qps = qps or offered_qps
        for sm in models:     # prime + discard warmup/previous-pass noise
            result_within(sm.batcher.submit(xs[sm.name]), BENCH_WAIT_S,
                          what="bench warmup")
            sm.metrics.snapshot(reset=True)
        futs = []
        t0 = time.perf_counter()
        i = 0
        while True:
            t_next = t0 + i / qps
            now = time.perf_counter()
            if t_next >= t0 + args.secs:
                break
            if t_next > now:
                time.sleep(t_next - now)
            sm = models[i % len(models)]
            # per-request sampling decision, exactly what the HTTP front
            # door does (None when untraced or unsampled)
            ctx = tracer.request_context() if tracer is not None else None
            try:
                futs.append(sm.batcher.submit(xs[sm.name], trace=ctx))
            except RequestRejected:
                pass          # shed — counted by the batcher's metrics
            i += 1
        gen_elapsed = time.perf_counter() - t0
        # under-load snapshot BEFORE the tail drains: completions during
        # the arrival window are the sustained rate; the drain tail would
        # flatter it
        under_load = {sm.name: sm.metrics.snapshot() for sm in models}
        for f in futs:
            result_within(f, BENCH_WAIT_S, what="bench request")
        final = {sm.name: sm.metrics.snapshot() for sm in models}
        sustained = (sum(s["requests"] for s in under_load.values())
                     / gen_elapsed)
        return sustained, under_load, final, i

    trace_report = {}
    if args.trace_out:
        from deepvision_tpu.obs.export import write_chrome_trace
        from deepvision_tpu.obs.trace import Tracer

        # the overhead comparison needs BOTH passes below saturation: at
        # the default 0.7x-estimate rate a 1-core host is already past
        # effective capacity, where pass-to-pass variance is 10-20% and
        # would swamp any 3% measurement (and the device-bound capacity
        # estimate itself is noisy). Self-calibrate: start at 45% of the
        # estimate and halve until the UNTRACED pass absorbs >=98% of the
        # schedule — below saturation the sustained rate is
        # schedule-stable (sub-1% run-to-run), so a tracing slowdown that
        # eats the headroom shows up as dropped completions.
        compare_qps = args.qps or round(0.45 * fleet_capacity, 1)
        while True:
            untraced_qps, _, _, _ = run_schedule(qps=compare_qps)
            if (args.qps or compare_qps < 50
                    or untraced_qps >= 0.98 * compare_qps):
                break
            compare_qps = round(compare_qps / 2.0, 1)
        tracer = Tracer()     # default sampling (DEEPVISION_TRACE_SAMPLE)
        for sm in models:
            sm.batcher.tracer = tracer
        sustained, under_load, final, offered = run_schedule(
            tracer, qps=compare_qps)
        offered_qps = compare_qps
        n_spans = write_chrome_trace(tracer, args.trace_out)
        ratio = sustained / untraced_qps if untraced_qps else 0.0
        trace_report = {
            "trace_out": args.trace_out,
            "trace_spans": n_spans,
            "trace_sample": tracer.sample,
            "untraced_qps": round(untraced_qps, 2),
            # the hot-path pin: tracing at default sampling must keep
            # sustained QPS within 3% of the untraced run
            "trace_overhead_ratio": round(ratio, 4),
            "trace_overhead_ok": bool(ratio >= 0.97),
        }
    else:
        sustained, under_load, final, offered = run_schedule()
    fleet.drain(timeout=30)

    shed = sum(s["shed_requests"] for s in final.values())
    p99 = max((s.get("p99_ms", 0.0) for s in under_load.values()),
              default=0.0)
    p50 = max((s.get("p50_ms", 0.0) for s in under_load.values()),
              default=0.0)
    shed_rate = shed / offered if offered else 0.0
    print(json.dumps({
        "metric": f"serve_fleet_sustained_qps(open-loop,1img/req,"
                  f"{'+'.join(names)},b{max_batch},"
                  f"delay{args.delay_ms:g}ms,{platform})",
        "value": round(sustained, 2),
        "unit": "req/sec",
        # goodput fraction: completions per offered arrival — 1.0 means the
        # fleet absorbed the schedule; well below it means queueing/shedding
        "vs_baseline": round(sustained / offered_qps, 3) if offered_qps
                       else 0.0,
        "baseline": f"offered open-loop arrival rate "
                    f"({offered_qps:g} req/s; vs_baseline is the goodput "
                    f"fraction completed at that rate)",
        "offered_qps": round(offered_qps, 1),
        "offered_requests": offered,
        "p50_ms_under_load": round(p50, 3),
        "p99_ms_under_load": round(p99, 3),
        "shed_requests": int(shed),
        "shed_rate": round(shed_rate, 4),
        "fleet_capacity_est_qps": round(fleet_capacity, 1),
        "models": {sm.name: {
            "requests": under_load[sm.name]["requests"],
            "p99_ms": round(under_load[sm.name].get("p99_ms", 0.0), 3),
            "shed_requests": int(final[sm.name]["shed_requests"]),
            "batch_compute_ms": round(batch_ms[sm.name], 3),
        } for sm in models},
        "secs": args.secs,
        "cpu_cores": os.cpu_count(),
        "platform": platform,
        "compile_cache": compilation_cache_stats(),
        **trace_report,
    }))
    if trace_report and not trace_report["trace_overhead_ok"]:
        raise SystemExit(
            f"tracing overhead broke the 3% bar: traced "
            f"{sustained:.1f} req/s vs untraced "
            f"{trace_report['untraced_qps']:.1f} req/s "
            f"(ratio {trace_report['trace_overhead_ratio']:.3f} < 0.97)")


def spike_bench(args) -> None:
    """Overload TRANSIENT bench: open-loop arrivals step 1x -> 3x -> 1x
    while the shed-driven autoscaler scales the dispatcher pools. Reports
    time-to-absorb (seconds from spike onset until the windowed shed rate
    returns — and stays — under 1%), shed during the transient, p99 per
    phase, scale-up decisions, and the recompile-free worker-spawn proof
    (per-model compile logs unchanged, jit caches empty). Baseline (1x)
    defaults to 50% of the measured fleet capacity estimate, so the spike
    (3x = 150%) genuinely overloads and the return to 1x is genuinely
    absorbable — the transient, not a permanent brown-out."""
    import jax

    from deepvision_tpu.cli import (compilation_cache_stats,
                                    setup_compilation_cache)
    setup_compilation_cache()

    from deepvision_tpu.serve.autoscale import AutoscaleController
    from deepvision_tpu.serve.batcher import (RequestRejected,
                                              result_within)
    from deepvision_tpu.serve.engine import PredictEngine
    from deepvision_tpu.serve.fleet import ModelFleet

    names = [s.strip() for s in args.models.split(",") if s.strip()]
    max_batch = args.max_batch
    fleet = ModelFleet()
    for name in names:
        engine = PredictEngine.from_config(
            name, buckets=(1, 8, 32), max_batch=max_batch, verbose=False)
        engine.warmup()
        fleet.add(engine, max_delay_ms=args.delay_ms,
                  max_queue_examples=4 * max_batch, workers=1)
    models = list(fleet)
    platform = jax.devices()[0].platform
    n_programs = {sm.name: len(sm.engine.compile_log) for sm in models}

    xs = {sm.name: np.random.RandomState(1).randn(
        1, *sm.engine.example_shape).astype(sm.engine.input_dtype)
        for sm in models}
    for sm in models:         # prime + discard warmup noise
        result_within(sm.batcher.submit(xs[sm.name]), BENCH_WAIT_S,
                      what="bench warmup")
        sm.metrics.snapshot(reset=True)

    # calibrate the 1x operating point from MEASURED effective capacity:
    # flood the fleet with the same generator discipline for ~0.5s and take
    # the completed-request rate. The device-bound estimate open_loop uses
    # (max_batch x models / batch compute) overstates what one dispatcher
    # worker sustains at single-image request sizes, where the per-request
    # host path dominates — a "1x baseline" above real capacity would put
    # the STEADY phase in brown-out and the transient would never end.
    cal_secs = 0.5
    cal_futs = []
    t_end = time.perf_counter() + cal_secs
    i = 0
    while time.perf_counter() < t_end:
        sm = models[i % len(models)]
        try:
            cal_futs.append(sm.batcher.submit(xs[sm.name]))
        except RequestRejected:
            pass
        i += 1
    effective_capacity = sum(
        sm.metrics.snapshot()["requests"] for sm in models) / cal_secs
    for f in cal_futs:
        result_within(f, BENCH_WAIT_S, what="bench calibration")
    for sm in models:
        sm.metrics.snapshot(reset=True)
    qps_base = args.qps or max(10.0, round(0.45 * effective_capacity, 1))
    qps_spike = 3.0 * qps_base

    # fast control loop for a seconds-long transient: one overloaded
    # sample is enough evidence (up_after=1) and the cooldown only needs
    # to outlast one sampling period
    ctl = AutoscaleController(
        models, interval_s=0.15, min_workers=1,
        max_workers=args.max_workers, up_after=1, down_after=200,
        cooldown_s=0.3)

    pre = max(1.0, args.secs)
    spike = args.secs
    post = 2.0 * args.secs      # the recovery window the absorb is timed in
    phases = [("steady", qps_base, pre), ("spike", qps_spike, spike),
              ("recovery", qps_base, post)]
    win = 0.25                  # shed-rate window (s) for time-to-absorb

    futs = []
    offered_w: dict = {}        # per-window arrival/shed counts
    shed_w: dict = {}
    phase_p99 = {}
    workers_at = {}
    ctl.start()
    t0 = time.perf_counter()
    t_phase = 0.0               # phase start, relative to t0
    try:
        for phase_name, qps, dur in phases:
            i = 0
            while True:
                t_next = t0 + t_phase + i / qps
                now = time.perf_counter()
                if t_next - t0 >= t_phase + dur:
                    break
                if t_next > now:
                    time.sleep(t_next - now)
                sm = models[i % len(models)]
                w = int((time.perf_counter() - t0) / win)
                offered_w[w] = offered_w.get(w, 0) + 1
                try:
                    futs.append(sm.batcher.submit(xs[sm.name]))
                except RequestRejected:
                    shed_w[w] = shed_w.get(w, 0) + 1
                i += 1
            t_phase += dur
            phase_p99[phase_name] = max(
                (sm.metrics.snapshot(reset=True).get("p99_ms", 0.0)
                 for sm in models), default=0.0)
            workers_at[phase_name] = {sm.name: sm.batcher.workers
                                      for sm in models}
        failed = 0
        for f in futs:
            try:
                result_within(f, BENCH_WAIT_S, what="bench request")
            except Exception:  # noqa: BLE001 — count, don't crash the report
                failed += 1
    finally:
        ctl.stop()
        fleet.drain(timeout=30)

    # time-to-absorb: last window at/after spike onset whose shed rate is
    # >= 1% marks the end of the transient
    spike_w = int(pre / win)
    absorbed_at = spike_w       # no shed at all => absorbed instantly
    for w in sorted(offered_w):
        if w >= spike_w and offered_w[w] > 0 \
                and shed_w.get(w, 0) / offered_w[w] >= 0.01:
            absorbed_at = w + 1
    time_to_absorb = absorbed_at * win - pre
    # shed over the transient (spike onset -> absorb point)
    t_offered = sum(v for w, v in offered_w.items()
                    if spike_w <= w < absorbed_at)
    t_shed = sum(v for w, v in shed_w.items()
                 if spike_w <= w < absorbed_at)
    offered = sum(offered_w.values())
    shed = sum(shed_w.values())
    # post-absorb shed rate: the "returns below 1% and STAYS there" claim
    a_offered = sum(v for w, v in offered_w.items() if w >= absorbed_at)
    a_shed = sum(v for w, v in shed_w.items() if w >= absorbed_at)
    absorbed_shed_rate = (a_shed / a_offered) if a_offered else 0.0
    scale_ups = sum(sm.autoscale_stats["scale_ups"] for sm in models)
    recompiles = sum(len(sm.engine.compile_log) - n_programs[sm.name]
                     for sm in models)
    jit_entries = sum(sm.engine._jitted._cache_size() for sm in models)
    print(json.dumps({
        "metric": f"serve_spike_time_to_absorb(open-loop,1x->3x->1x,"
                  f"{'+'.join(names)},b{max_batch},"
                  f"delay{args.delay_ms:g}ms,{platform})",
        "value": round(time_to_absorb, 2),
        "unit": "sec",
        # post-absorb shed rate over the 1% bar: < 1.0 means the fleet
        # genuinely absorbed the transient (and stayed absorbed)
        "vs_baseline": round(absorbed_shed_rate / 0.01, 3),
        "baseline": "1% shed bar (vs_baseline = post-absorb shed rate / "
                    "0.01; < 1 means the spike was absorbed)",
        "qps_base": round(qps_base, 1),
        "qps_spike": round(qps_spike, 1),
        "phase_secs": {"steady": pre, "spike": spike, "recovery": post},
        "offered_requests": offered,
        "shed_requests": shed,
        "shed_during_transient": t_shed,
        "shed_rate_transient": round(t_shed / t_offered, 4) if t_offered
                               else 0.0,
        "post_absorb_shed_rate": round(absorbed_shed_rate, 4),
        "time_to_absorb_s": round(time_to_absorb, 2),
        "p99_ms_steady": round(phase_p99.get("steady", 0.0), 3),
        "p99_ms_spike": round(phase_p99.get("spike", 0.0), 3),
        "p99_ms_recovery": round(phase_p99.get("recovery", 0.0), 3),
        "scale_ups": scale_ups,
        "workers": workers_at,
        "responses_failed": failed,
        # the recompile-free worker-spawn proof: the AOT bucket caches are
        # untouched and nothing fell back to silent jit
        "recompiles": recompiles,
        "jit_cache_entries": jit_entries,
        "effective_capacity_qps": round(effective_capacity, 1),
        "cpu_cores": os.cpu_count(),
        "platform": platform,
        "compile_cache": compilation_cache_stats(),
    }))


def promote_under_load(args) -> None:
    """Open-loop arrivals (same schedule discipline as `open_loop`) with a
    full promotion cycle triggered mid-bench: at `--promote-at` seconds a
    new checkpoint epoch is committed into the first model's run dir and
    the hot-reload sweep runs the shadow -> gate -> canary ->
    promote/rollback pipeline while arrivals keep firing. One
    bench.py-schema line: `value` is promotion_secs (restore + shadow +
    canary + flip, wall clock), `vs_baseline` is p99-through-the-swap over
    steady-state p99 (the "p99 flat through a swap" claim — the acceptance
    bar is <= 1.5), plus shed rate and the zero-failed /
    zero-mixed-generation audit over every response of the promoted
    model."""
    import shutil
    import tempfile
    import threading as _threading

    import jax

    from deepvision_tpu.cli import (compilation_cache_stats,
                                    setup_compilation_cache)
    setup_compilation_cache()

    from deepvision_tpu.configs import get_config, trainer_class_for_config
    from deepvision_tpu.core.metrics import MetricsLogger
    from deepvision_tpu.serve.batcher import (RequestRejected,
                                              result_within)
    from deepvision_tpu.serve.engine import PredictEngine
    from deepvision_tpu.serve.fleet import ModelFleet
    from deepvision_tpu.serve.promote import PromotionController
    from deepvision_tpu.serve.reload import WeightReloader

    names = [s.strip() for s in args.models.split(",") if s.strip()]
    max_batch = args.max_batch
    target = names[0]            # the model the promotion cycle runs on
    cfg = get_config(target)
    sample = (cfg.data.image_size, cfg.data.image_size, cfg.data.channels)

    def commit_epoch(workdir, epoch, state=None, scale=None):
        trainer = trainer_class_for_config(target)(cfg, workdir=workdir)
        try:
            trainer.init_state(sample)
            st = state if state is not None else trainer.state
            if scale:
                st = st.replace(params=jax.tree_util.tree_map(
                    lambda a: a * scale, st.params))
            trainer.ckpt.save(epoch, st, {"best_metric": 0.0})
            trainer.ckpt.flush()
            return trainer.state
        finally:
            trainer.close()

    tmpdir = tempfile.mkdtemp(prefix="bench_promote_")
    workdir = os.path.join(tmpdir, target)
    state1 = commit_epoch(workdir, 1)

    fleet = ModelFleet()
    logger = MetricsLogger(tmpdir, name="serve")
    # warm the metrics stream NOW: the first logged event lazily builds the
    # TensorBoard writer (a multi-second import on a busy 1-core host) —
    # paying that inside the promotion cycle would be charged to
    # promotion_secs and smear p99 through the swap
    logger.log(0, {"promote_bench_armed": 1.0}, prefix="resilience_",
               echo=False)
    try:
        for i, name in enumerate(names):
            engine = PredictEngine.from_config(
                name, workdir=workdir if i == 0 else None,
                buckets=(1, 8, 32), max_batch=max_batch, verbose=False)
            engine.warmup()
            fleet.add(engine, workdir=workdir if i == 0 else None,
                      max_delay_ms=args.delay_ms,
                      max_queue_examples=8 * max_batch)
        models = list(fleet)
        sm0 = models[0]
        promoter = PromotionController(
            sm0, canary_frac=args.canary_frac,
            canary_window_s=args.canary_window, logger=logger)
        reloader = WeightReloader(fleet, poll_every_s=0, logger=logger)
        platform = jax.devices()[0].platform
        n_programs = len(sm0.engine.compile_log)

        batch_ms = {sm.name: sm.engine.measure_batch_ms(max_batch)
                    for sm in models}
        fleet_capacity = (max_batch * len(models)
                          / (sum(batch_ms.values()) / 1000.0))
        # a HEALTHY operating point (~20% of the capacity estimate), not
        # the saturation point the plain --load bench probes: the claim
        # under test is "p99 flat through a promotion", which is only
        # meaningful where steady-state p99 is the deadline floor rather
        # than queueing noise
        offered_qps = args.qps or round(0.2 * fleet_capacity, 1)

        xs = {sm.name: np.random.RandomState(1).randn(
            1, *sm.engine.example_shape).astype(sm.engine.input_dtype)
            for sm in models}
        for sm in models:
            result_within(sm.submit(xs[sm.name]), BENCH_WAIT_S,
                          what="bench warmup")
            sm.metrics.snapshot(reset=True)
        ref_old = sm0.engine.reference(xs[target])
        # the candidate epoch is committed BEFORE the arrival schedule
        # starts: in production the TRAINING job pays the save (on its own
        # host); the serving-side cycle this bench measures is
        # verify -> restore -> shadow -> canary -> flip, which begins when
        # the reload sweep first sees the committed epoch at --promote-at
        commit_epoch(workdir, 2, state1, scale=1.05)

        secs = max(args.secs, args.promote_at + 2.0)
        stats = {"steady": None, "swap": None, "promotion_secs": None}

        def trigger():
            # steady-state window closes exactly when the cycle starts; the
            # swap window covers verify + restore + shadow + canary + flip
            stats["steady"] = sm0.metrics.snapshot(reset=True)
            t0 = time.perf_counter()
            reloader.check_once()
            stats["promotion_secs"] = time.perf_counter() - t0
            stats["swap"] = sm0.metrics.snapshot(reset=True)

        trig = _threading.Thread(target=trigger, daemon=True)
        futs = []        # the promoted model's (future) answers, audited
        t0 = time.perf_counter()
        i = 0
        started = False
        while True:
            t_next = t0 + i / offered_qps
            now = time.perf_counter()
            if not started and now - t0 >= args.promote_at:
                started = True
                trig.start()
            if t_next >= t0 + secs:
                break
            if t_next > now:
                time.sleep(t_next - now)
            sm = models[i % len(models)]
            try:
                fut = sm.submit(xs[sm.name])
                if sm is sm0:
                    futs.append(fut)
            except RequestRejected:
                pass          # shed — counted by the batcher's metrics
            i += 1
        offered = i
        trig.join(timeout=600)
        results, failed = [], 0
        for f in futs:
            try:
                results.append(np.asarray(
                    result_within(f, BENCH_WAIT_S, what="bench request")))
            except Exception:  # noqa: BLE001 — every failure is the point
                failed += 1
        final = {sm.name: sm.metrics.snapshot() for sm in models}

        decision = (promoter.history[-1] if promoter.history
                    else {"decision": "none"})
        # second reference for the mixed-generation audit: after a promote
        # the live weights ARE the candidate's; after a rollback, re-stage
        # the exact epoch-2 weights (live params x 1.05, the scale the
        # trigger committed) on the now-idle engine to recover what the
        # canary cohort saw
        if decision["decision"] == "promoted":
            ref_new = sm0.engine.reference(xs[target])
        else:
            live = jax.device_get(sm0.engine._variables)
            cand = dict(live, params=jax.tree_util.tree_map(
                lambda a: np.asarray(a) * 1.05, live["params"]))
            sm0.engine.stage_candidate(cand)
            ref_new = sm0.engine.reference(xs[target],
                                           generation="candidate")
            sm0.engine.drop_candidate()
        n_old = n_new = n_mixed = 0
        for out in results:
            if np.allclose(out, ref_old, rtol=1e-4, atol=1e-5):
                n_old += 1
            elif np.allclose(out, ref_new, rtol=1e-4, atol=1e-5):
                n_new += 1
            else:
                n_mixed += 1

        shed = sum(s["shed_requests"] for s in final.values())
        steady_p99 = (stats["steady"] or {}).get("p99_ms", 0.0)
        swap_p99 = (stats["swap"] or {}).get("p99_ms", 0.0)
        p99_ratio = (swap_p99 / steady_p99) if steady_p99 else 0.0
        resilience_events = sorted(
            k for k in logger.history if k.startswith("resilience_promote_"))
        print(json.dumps({
            "metric": f"serve_promotion_under_load(open-loop,1img/req,"
                      f"{'+'.join(names)},b{max_batch},"
                      f"canary{args.canary_frac:g}@{args.canary_window:g}s,"
                      f"{platform})",
            "value": round(stats["promotion_secs"] or 0.0, 3),
            "unit": "sec",
            # p99 through the swap window over steady-state p99: the
            # "p99 flat through a promotion" claim; acceptance bar <= 1.5
            "vs_baseline": round(p99_ratio, 3),
            "baseline": f"steady-state p99 before the cycle "
                        f"({steady_p99:.3f} ms; vs_baseline is "
                        f"p99-through-the-swap over it, bar <= 1.5)",
            "decision": decision["decision"],
            "promotion_secs": round(stats["promotion_secs"] or 0.0, 3),
            "shadow_canary_secs": decision.get("secs"),
            "weights_epoch": sm0.engine.provenance["checkpoint_epoch"],
            "offered_qps": round(offered_qps, 1),
            "offered_requests": offered,
            "p99_ms_steady": round(steady_p99, 3),
            "p99_ms_through_swap": round(swap_p99, 3),
            "shed_requests": int(shed),
            "shed_rate": round(shed / offered, 4) if offered else 0.0,
            "responses_old_gen": n_old,
            "responses_new_gen": n_new,
            "responses_mixed": n_mixed,
            "responses_failed": failed,
            "canary_requests": decision.get("canary_requests"),
            "recompiles": len(sm0.engine.compile_log) - n_programs,
            "resilience_events": resilience_events,
            "secs": secs,
            "cpu_cores": os.cpu_count(),
            "platform": platform,
            "compile_cache": compilation_cache_stats(),
        }))
    finally:
        fleet.drain(timeout=30)
        logger.close()
        shutil.rmtree(tmpdir, ignore_errors=True)


def flywheel_record(*, model_name, platform, max_batch, time_to_detect_s,
                    time_to_promoted_s, goodput_rps_steady,
                    goodput_rps_episode, detect_windows, hysteresis_windows,
                    finetune_epoch, decision, flywheel_id, responses_total,
                    responses_failed, shed_requests, recompiles, counters,
                    compile_cache) -> dict:
    """The `--flywheel` bench line (bench.py schema), built pure from
    measured inputs so the CI schema test can pin its shape without paying
    for the bench. The headline `value` is time-to-promoted (confirmed
    drift -> retrained candidate live, wall clock); `vs_baseline` is
    serving goodput DURING the episode over steady-state goodput — the
    "the flywheel must not shed healthy traffic" claim. The hard bars the
    bench itself enforces are zero failed responses and zero shed across
    the whole run; the goodput ratio is reported for the capacity plan
    (fine-tune and serving share the host's cores on CPU, so a dip is
    honest — shed or failure is not)."""
    ratio = (goodput_rps_episode / goodput_rps_steady
             if goodput_rps_steady else 0.0)
    return {
        "metric": f"serve_flywheel_time_to_promoted({model_name},"
                  f"b{max_batch},drift-fault,{platform})",
        "value": round(time_to_promoted_s, 3),
        "unit": "sec",
        # goodput during the drift->retrain->promote episode over steady
        # state: the episode must not shed healthy traffic
        "vs_baseline": round(ratio, 3),
        "baseline": f"steady-state goodput before the monitor arms "
                    f"({goodput_rps_steady:.1f} rsp/s; vs_baseline is "
                    f"goodput during the episode over it — zero shed and "
                    f"zero failures are the hard bars)",
        "time_to_detect_s": round(time_to_detect_s, 3),
        "time_to_promoted_s": round(time_to_promoted_s, 3),
        "goodput_rps_steady": round(goodput_rps_steady, 1),
        "goodput_rps_episode": round(goodput_rps_episode, 1),
        "detect_windows": int(detect_windows),
        "hysteresis_windows": int(hysteresis_windows),
        "finetune_epoch": int(finetune_epoch),
        "decision": decision,
        "flywheel_id": flywheel_id,
        "responses_total": int(responses_total),
        "responses_failed": int(responses_failed),
        "shed_requests": int(shed_requests),
        "recompiles": int(recompiles),
        "counters": dict(counters),
        "cpu_cores": os.cpu_count(),
        "platform": platform,
        "compile_cache": compile_cache,
    }


def flywheel_bench(args) -> None:
    """The serve->train->serve flywheel under closed-loop load
    (docs/FAILURES.md "Flywheel decisions"): the DRIFT_SHIFT fault is
    armed from the first reservoir window, synthetic clients hammer the
    batcher, and the bench drives the monitor tick-by-tick — measuring
    time-to-detect (monitor armed -> hysteresis streak confirmed),
    time-to-promoted (confirmed -> the fine-tuned epoch live through the
    shadow/canary gate), and serving goodput through the whole episode.
    Hard bars: zero failed responses, zero shed, zero serve-path
    recompiles, decision == promoted."""
    import shutil
    import tempfile
    import threading as _threading

    import jax

    from deepvision_tpu.cli import (compilation_cache_stats,
                                    setup_compilation_cache)
    setup_compilation_cache()

    from deepvision_tpu.configs import get_config, trainer_class_for_config
    from deepvision_tpu.core.metrics import MetricsLogger
    from deepvision_tpu.flywheel import FlywheelController
    from deepvision_tpu.serve.batcher import result_within
    from deepvision_tpu.serve.engine import PredictEngine
    from deepvision_tpu.serve.fleet import ModelFleet
    from deepvision_tpu.serve.promote import PromotionController
    from deepvision_tpu.utils.faults import FaultInjector

    target = "lenet5"
    cfg = get_config(target)
    sample = (cfg.data.image_size, cfg.data.image_size, cfg.data.channels)
    tmpdir = tempfile.mkdtemp(prefix="bench_flywheel_")
    workdir = os.path.join(tmpdir, target)

    trainer = trainer_class_for_config(target)(cfg, workdir=workdir)
    try:
        trainer.init_state(sample)
        trainer.ckpt.save(1, trainer.state, {"best_metric": 0.0})
        trainer.ckpt.flush()
    finally:
        trainer.close()

    fleet = ModelFleet()
    logger = MetricsLogger(tmpdir, name="serve")
    # warm the metrics stream NOW: the first logged event lazily builds the
    # TensorBoard writer — paying that inside the episode would be charged
    # to time_to_promoted
    logger.log(0, {"flywheel_bench_armed": 1.0}, prefix="resilience_",
               echo=False)
    try:
        engine = PredictEngine.from_config(target, workdir=workdir,
                                           buckets=(1, 4, 8), verbose=False)
        engine.warmup()
        sm = fleet.add(engine, workdir=workdir, max_delay_ms=2.0)
        PromotionController(sm, canary_frac=0.25, canary_window_s=0.2,
                            logger=logger)
        hysteresis = 2
        fw = FlywheelController(
            sm, tick_every_s=0, logger=logger,
            finetune_epochs=1, finetune_batches=4,
            faults=FaultInjector(drift_shift_window=0,
                                 drift_shift_magnitude=3.0),
            window_examples=32, sample_per_batch=4,
            hysteresis_windows=hysteresis)
        platform = jax.devices()[0].platform
        n_programs = len(engine.compile_log)
        x = np.random.RandomState(0).randn(
            4, *engine.example_shape).astype(engine.input_dtype)
        result_within(sm.submit(x), BENCH_WAIT_S, what="bench warmup")
        sm.metrics.snapshot(reset=True)

        stop = _threading.Event()
        done_ts: list = []          # completion timestamps, merged later
        failures: list = []

        def client(i: int) -> None:
            rs = np.random.RandomState(i)
            xi = rs.randn(4, *engine.example_shape).astype(
                engine.input_dtype)
            ts = []
            while not stop.is_set():
                try:
                    result_within(sm.submit(xi), BENCH_WAIT_S,
                                  what="bench request")
                    ts.append(time.perf_counter())
                except Exception as e:  # noqa: BLE001 — every failure
                    failures.append(e)  # fails the bench's hard bar
                    break
            done_ts.extend(ts)          # list.extend is atomic enough here

        threads = [_threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(3)]
        t_traffic = time.perf_counter()
        for t in threads:
            t.start()

        # phase 1 — steady state, monitor idle: the goodput baseline
        steady_secs = 2.0
        time.sleep(steady_secs)
        t_arm = time.perf_counter()

        # phase 2 — the monitor ticks: drift (present from window 0 via
        # the fault) must confirm through the hysteresis streak
        fid = None
        deadline = t_arm + 120.0
        while fid is None and time.perf_counter() < deadline:
            fid = fw.monitor.tick()
            if fid is None:
                time.sleep(0.02)
        if fid is None:
            raise SystemExit(f"drift never confirmed: "
                             f"{fw.monitor.describe()}")
        t_detect = time.perf_counter()
        detect_windows = fw.monitor.windows

        # phase 3 — the episode, synchronous on this thread: fine-tune ->
        # gate -> canary -> promote, while the clients keep firing
        state = fw.tick()
        t_promoted = time.perf_counter()
        if state != "promoted":
            raise SystemExit(f"flywheel episode did not promote: {state} "
                             f"{fw.describe()}")

        time.sleep(0.5)             # a beat of post-episode serving
        stop.set()
        for t in threads:
            t.join(timeout=60)

        snap = sm.metrics.snapshot()
        shed = snap.get("shed_requests", 0)
        epoch = engine.provenance["checkpoint_epoch"]
        recompiles = len(engine.compile_log) - n_programs
        if failures:
            raise SystemExit(f"failed responses during the episode: "
                             f"{failures[:1]!r}")
        if shed:
            raise SystemExit(f"the flywheel episode shed {shed} healthy "
                             f"requests")
        if recompiles:
            raise SystemExit(f"{recompiles} serve-path recompiles during "
                             f"the episode")

        def goodput(t0: float, t1: float) -> float:
            n = sum(1 for t in done_ts if t0 <= t < t1)
            return n / (t1 - t0) if t1 > t0 else 0.0

        print(json.dumps(flywheel_record(
            model_name=target, platform=platform,
            max_batch=engine.max_batch,
            time_to_detect_s=t_detect - t_arm,
            time_to_promoted_s=t_promoted - t_detect,
            goodput_rps_steady=goodput(t_traffic + 0.5, t_arm),
            goodput_rps_episode=goodput(t_detect, t_promoted),
            detect_windows=detect_windows,
            hysteresis_windows=hysteresis,
            finetune_epoch=epoch, decision=fw.last_decision,
            flywheel_id=fw.last_flywheel_id,
            responses_total=len(done_ts), responses_failed=len(failures),
            shed_requests=shed, recompiles=recompiles,
            counters=fw.counters,
            compile_cache=compilation_cache_stats())))
    finally:
        fleet.drain(timeout=30)
        logger.close()
        shutil.rmtree(tmpdir, ignore_errors=True)


def int8_bench() -> None:
    """int8-vs-bf16 serving comparison (docs/SERVING.md "Quantized
    serving"): one engine, both precision ladders compiled in its AOT
    cache, the SAME closed-loop load driven through the same micro-batcher
    at each precision — sustained images/sec, p99 at an overload-free
    operating point, and bytes/batch (the weight bytes one dispatch reads
    + the input batch), as one bench.py-schema line.

    The byte cut is the hardware-portable claim (the r05 regime is
    bandwidth-bound, and int8 weights are ~4x smaller than the f32 tree
    the bf16 buckets dispatch with). The THROUGHPUT ratio is reported
    honestly per platform: XLA:CPU has no fast int8 conv path, so on a CPU
    host vs_bf16 is typically <= 1 — the ratio is the TPU story, the gate
    and the byte accounting are what this bench proves everywhere. A
    refused gate (arm DEEPVISION_FAULT_QUANT_REGRESS=1 to rehearse) still
    emits the line, with the refusal decision and no int8 phase."""
    model_name = os.environ.get("DEEPVISION_SERVE_BENCH_MODEL", "lenet5")
    secs = float(os.environ.get("DEEPVISION_SERVE_BENCH_SECS", "2.0"))
    max_delay_ms = float(os.environ.get("DEEPVISION_SERVE_BENCH_DELAY_MS",
                                        "5.0"))
    max_batch = int(os.environ.get("DEEPVISION_SERVE_BENCH_MAX_BATCH", "32"))

    import jax

    from deepvision_tpu.cli import (compilation_cache_stats,
                                    setup_compilation_cache)
    setup_compilation_cache()

    from deepvision_tpu.ops.quant import tree_nbytes
    from deepvision_tpu.serve.batcher import (DynamicBatcher,
                                              RequestRejected,
                                              result_within)
    from deepvision_tpu.serve.engine import PredictEngine
    from deepvision_tpu.serve.metrics import ServingMetrics
    from deepvision_tpu.serve.quantize import arm_int8

    engine = PredictEngine.from_config(
        model_name, buckets=(1, 8, 32), max_batch=max_batch)
    engine.warmup()
    platform = jax.devices()[0].platform
    decision = arm_int8(engine)         # calibrate + compile + GATE
    engine.warmup()                     # absorb the int8 first-dispatch too
    int8_live = decision["decision"] == "int8_enabled"

    metrics = ServingMetrics(window=8192)
    batcher = DynamicBatcher(engine, max_delay_ms=max_delay_ms,
                             max_queue_examples=64 * max_batch,
                             metrics=metrics)
    x1 = np.random.RandomState(0).randn(
        1, *engine.example_shape).astype(engine.input_dtype)

    def sustained(precision: str) -> float:
        """Closed-loop saturation at one precision through the batcher."""
        stop = threading.Event()

        def client(i: int) -> None:
            xi = np.random.RandomState(i).randn(
                1, *engine.example_shape).astype(engine.input_dtype)
            while not stop.is_set():
                try:
                    result_within(batcher.submit(xi, precision=precision),
                                  BENCH_WAIT_S, what="bench request")
                except RequestRejected:
                    time.sleep(0.001)

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(min(128, 3 * max_batch))]
        for t in threads:
            t.start()
        time.sleep(0.25)             # fill the pipeline before timing
        metrics.snapshot(reset=True)
        time.sleep(secs)
        thr = metrics.snapshot(reset=True)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        return thr["images_per_sec"]

    def p99_at(precision: str, rate: float) -> float:
        """p99 at ~20% of that precision's capacity (overload-free)."""
        metrics.snapshot(reset=True)
        tick, futs, end = 0.002, [], time.perf_counter() + secs
        per_tick = max(1, int(rate * tick))
        while time.perf_counter() < end:
            for _ in range(per_tick):
                try:
                    futs.append(batcher.submit(x1, precision=precision))
                except RequestRejected:
                    pass
            time.sleep(tick)
        for f in futs:
            result_within(f, BENCH_WAIT_S, what="bench request")
        return metrics.snapshot().get("p99_ms", float("inf"))

    bf16_ips = sustained("bf16")
    bf16_p99 = p99_at("bf16", max(50.0, 0.2 * bf16_ips))
    int8_ips = int8_p99 = None
    if int8_live:
        int8_ips = sustained("int8")
        int8_p99 = p99_at("int8", max(50.0, 0.2 * int8_ips))
    batcher.drain(timeout=30)

    input_bytes = int(np.zeros(
        (max_batch, *engine.example_shape), engine.input_dtype).nbytes)
    wb_bf16 = decision["weight_bytes_bf16"]
    wb_int8 = decision["weight_bytes_int8"]
    print(json.dumps({
        "metric": f"serve_int8_images_per_sec(1img/req,{model_name},"
                  f"b{max_batch},delay{max_delay_ms:g}ms,{platform})",
        "value": round(int8_ips, 2) if int8_ips else 0.0,
        "unit": "images/sec",
        # int8 vs bf16 sustained throughput, same engine/batcher/load —
        # <= 1 on CPU (no fast int8 conv path in XLA:CPU), the byte cut
        # below is the bandwidth-bound (TPU) lever either way
        "vs_bf16": (round(int8_ips / bf16_ips, 3)
                    if int8_ips and bf16_ips else 0.0),
        "bf16_images_per_sec": round(bf16_ips, 2),
        "p99_ms_bf16": round(bf16_p99, 3),
        "p99_ms_int8": round(int8_p99, 3) if int8_p99 is not None else None,
        # bytes one max-batch dispatch reads: the quantized weight tree +
        # the uint8/f32 input batch, vs the f32 tree the bf16 ladder reads
        "bytes_per_batch_bf16": wb_bf16 + input_bytes,
        "bytes_per_batch_int8": (wb_int8 + input_bytes
                                 if int8_live else None),
        "weight_bytes_ratio": round(wb_bf16 / wb_int8, 2) if wb_int8 else 0.0,
        "quant_gate": {k: decision[k] for k in
                       ("decision", "watch", "metric_bf16", "metric_int8",
                        "delta", "gate", "quantized_eqns",
                        "calibration_examples")},
        "buckets": list(engine.buckets),
        "cpu_cores": os.cpu_count(),
        "platform": platform,
        "compile_cache": compilation_cache_stats(),
    }))
    # live int8 must still be an accuracy-gated deployment, and the weight
    # byte cut is the hard bar (>= 1.8x, the jaxvet QUANT rule's floor)
    if int8_live and wb_bf16 < 1.8 * wb_int8:
        raise SystemExit(f"int8 weight bytes {wb_int8} vs bf16 {wb_bf16}: "
                         f"cut below the 1.8x bar")


def mesh_record(*, model_name, platform, n_devices, mesh_axes, max_batch,
                wb_single, wb_mesh, wb_mesh_int8, parity_max_abs_err,
                p99_ms_single, p99_ms_mesh, batch_ms_single, batch_ms_mesh,
                recompiles, jit_cache_entries, largest_servable,
                compile_cache) -> dict:
    """The `--mesh` bench line (bench.py schema), built pure from measured
    inputs so the CI schema test can pin its shape without paying for the
    bench. The headline `value` is per-chip resident weight bytes on the
    mesh; `vs_baseline` is the single-chip engine's figure over it — the
    cut the model axis buys, with the acceptance bar
    `vs_baseline >= 0.98 * mesh["model"]` (0.98 absorbs the handful of
    small unsharded leaves below the serve-side sharding floor)."""
    model_axis = int(mesh_axes.get("model", 1))
    cut = (wb_single / wb_mesh) if wb_mesh else 0.0
    return {
        "metric": f"serve_mesh_per_chip_weight_bytes({model_name},"
                  f"mesh={'x'.join(f'{k}{v}' for k, v in mesh_axes.items())},"
                  f"b{max_batch},{platform})",
        "value": int(wb_mesh),
        "unit": "bytes/chip",
        # per-chip weight bytes: single-chip engine over the mesh engine —
        # the acceptance bar is >= 0.98 * the model-axis size
        "vs_baseline": round(cut, 3),
        "baseline": f"single-chip engine per-chip resident weight bytes "
                    f"({wb_single}; vs_baseline is its ratio over the mesh "
                    f"engine's, bar >= {0.98 * model_axis:g})",
        "mesh": dict(mesh_axes),
        "devices": int(n_devices),
        "weight_bytes_per_chip_single": int(wb_single),
        "weight_bytes_per_chip_mesh": int(wb_mesh),
        "weight_bytes_per_chip_mesh_int8": (int(wb_mesh_int8)
                                            if wb_mesh_int8 else None),
        "parity_max_abs_err": float(parity_max_abs_err),
        "p99_ms_batch_max_single": round(p99_ms_single, 3),
        "p99_ms_batch_max_mesh": round(p99_ms_mesh, 3),
        "batch_compute_ms_single": round(batch_ms_single, 3),
        "batch_compute_ms_mesh": round(batch_ms_mesh, 3),
        # the zero-recompile proof across a staged promotion on the mesh
        # engine: compile-log delta and the jit fallback cache size
        "recompiles": int(recompiles),
        "jit_cache_entries": int(jit_cache_entries),
        "largest_servable": largest_servable,
        "cpu_cores": os.cpu_count(),
        "platform": platform,
        "compile_cache": compile_cache,
    }


def mesh_bench(args) -> None:
    """Mesh-sharded vs single-chip predict (see module docstring `--mesh`).
    Needs >= --model-parallel devices; `make bench-serve-mesh` runs it on
    8 CPU virtual devices."""
    import jax

    from deepvision_tpu.cli import (compilation_cache_stats,
                                    setup_compilation_cache)
    setup_compilation_cache()

    from deepvision_tpu.configs import (CONFIGS, get_config,
                                        trainer_class_for_config)
    from deepvision_tpu.parallel.mesh import make_mesh
    from deepvision_tpu.serve.engine import PredictEngine

    model_name = os.environ.get("DEEPVISION_SERVE_BENCH_MODEL", "lenet5")
    max_batch = args.max_batch
    platform = jax.devices()[0].platform
    n_devices = len(jax.devices())
    need = args.model_parallel * args.spatial_parallel
    if n_devices < need or n_devices % need:
        raise SystemExit(
            f"mesh bench: {n_devices} devices for model_parallel="
            f"{args.model_parallel} x spatial_parallel="
            f"{args.spatial_parallel} — run under XLA_FLAGS="
            f"--xla_force_host_platform_device_count=8 (make "
            f"bench-serve-mesh)")
    mesh = make_mesh(model_parallel=args.model_parallel,
                     spatial_parallel=args.spatial_parallel)
    mesh_axes = dict(mesh.shape)

    single = PredictEngine.from_config(
        model_name, buckets=(1, 8, 32), max_batch=max_batch, verbose=False)
    single.warmup()
    sharded = PredictEngine.from_config(
        model_name, buckets=(1, 8, 32), max_batch=max_batch, verbose=False,
        mesh=mesh)
    sharded.warmup()

    # -- per-chip resident weight bytes (the headline) ---------------------
    wb_single = single.weight_bytes_per_chip()["bf16"]
    wb_mesh = sharded.weight_bytes_per_chip()["bf16"]

    # -- output parity on one max-batch bucket (same fresh-init weights) ---
    xb = np.random.RandomState(0).randn(
        max_batch, *single.example_shape).astype(single.input_dtype)
    out_single = np.asarray(single.predict(xb), dtype=np.float64)
    out_mesh = np.asarray(sharded.predict(xb), dtype=np.float64)
    parity = float(np.max(np.abs(out_single - out_mesh)))

    # -- p99 at the max-batch bucket, both engines -------------------------
    def p99_batch_max(engine) -> tuple:
        times = []
        for _ in range(30):
            t0 = time.perf_counter()
            engine.predict(xb)
            times.append((time.perf_counter() - t0) * 1000.0)
        return float(np.percentile(times, 99)), float(np.median(times))

    p99_single, med_single = p99_batch_max(single)
    p99_mesh, med_mesh = p99_batch_max(sharded)

    # -- zero recompiles ACROSS A PROMOTION on the mesh engine -------------
    n_programs = len(sharded.compile_log)
    live = jax.device_get(sharded._variables)
    cand = dict(live, params=jax.tree_util.tree_map(
        lambda a: np.asarray(a) * 1.05, live["params"]))
    sharded.stage_candidate(cand)
    sharded.predict(xb, generation="candidate")    # the shadow dispatch
    sharded.promote_candidate()
    sharded.predict(xb)                            # post-promotion dispatch
    recompiles = len(sharded.compile_log) - n_programs
    jit_entries = sharded._jitted._cache_size()

    # -- largest registered config servable per chip budget ----------------
    # analytic (jax.eval_shape over each config's init — no weights ever
    # materialized), under the same shapes->spec rule the engine places
    # with: which models fit `--hbm-gb` per chip single-chip vs mesh?
    import jax.numpy as jnp

    from deepvision_tpu.core.trainer import build_model_from_config
    from deepvision_tpu.parallel.mesh import analytic_per_chip_bytes
    budget = int(args.hbm_gb * (1 << 30))
    rows = []
    for name in CONFIGS.names():
        if trainer_class_for_config(name) is None:
            continue            # adversarial configs don't serve
        try:
            cfg = get_config(name)
            model, mcfg = build_model_from_config(cfg)
            sz = mcfg.data.image_size
            S = jax.ShapeDtypeStruct
            shaped = jax.eval_shape(
                lambda r, x: model.init(
                    {"params": r, "dropout": jax.random.fold_in(r, 1)},
                    x, train=True),
                S((2,), jnp.uint32),
                S((2, sz, sz, mcfg.data.channels), jnp.float32))
        except Exception:  # noqa: BLE001 — non-servable family: not scanned
            continue
        rows.append((name, analytic_per_chip_bytes(shaped),
                     analytic_per_chip_bytes(shaped, mesh)))

    def largest_fitting(idx: int):
        fitting = [r for r in rows if r[idx] <= budget]
        if not fitting:
            return None
        best = max(fitting, key=lambda r: r[idx])
        return {"model": best[0], "bytes_per_chip": int(best[idx])}

    largest = {
        "budget_gib": args.hbm_gb,
        "configs_scanned": len(rows),
        "fits_single_chip": sum(1 for r in rows if r[1] <= budget),
        "fits_mesh": sum(1 for r in rows if r[2] <= budget),
        "largest_single_chip": largest_fitting(1),
        "largest_mesh": largest_fitting(2),
    }

    print(json.dumps(mesh_record(
        model_name=model_name, platform=platform, n_devices=n_devices,
        mesh_axes=mesh_axes, max_batch=max_batch,
        wb_single=wb_single, wb_mesh=wb_mesh,
        wb_mesh_int8=sharded.weight_bytes_per_chip()["int8"],
        parity_max_abs_err=parity,
        p99_ms_single=p99_single, p99_ms_mesh=p99_mesh,
        batch_ms_single=med_single, batch_ms_mesh=med_mesh,
        recompiles=recompiles, jit_cache_entries=jit_entries,
        largest_servable=largest,
        compile_cache=compilation_cache_stats())))

    bars = []
    model_axis = int(mesh_axes.get("model", 1))
    if wb_single < 0.98 * model_axis * wb_mesh:
        bars.append(f"per-chip weight bytes {wb_mesh} vs single-chip "
                    f"{wb_single}: cut {wb_single / wb_mesh:.3f}x below the "
                    f"{0.98 * model_axis:g}x bar")
    if recompiles or jit_entries:
        bars.append(f"promotion on the mesh engine was not recompile-free: "
                    f"{recompiles} recompiles, {jit_entries} jit cache "
                    f"entries")
    if parity > 1e-4:
        bars.append(f"mesh predict diverged from the single-chip engine "
                    f"(max abs err {parity:.2e} > 1e-4)")
    if bars:
        raise SystemExit("mesh bench bars broke: " + "; ".join(bars))


def tier_bench(args) -> None:
    """Replica-tier bench (serve/tier.py), two phases on one shared
    persistent compile-cache dir:

    A) WARM BOOT — boot one replica process cold (empty cache) and time
       Popen -> first 200 from /predict, then boot a second replica on the
       SAME cache and time it again. The warm boot must be >=2x faster and
       its /healthz compile stats must show zero cache misses — the "a
       respawned replica is serving-warm in seconds" contract the tier's
       supervised restart depends on. Uses a compile-heavy small model
       (yolov3_digits) so the cache covers compile time, not import time,
       and pins DEEPVISION_CACHE_MIN_COMPILE_SECS=0 so sub-second bucket
       compiles persist too.

    B) KILL ONE OF N — three supervised lenet5 replicas behind a live
       TierRouter; an open-loop arrival schedule fires at the router while
       SIGKILL lands on replica 0 a third of the way in. Bars: ZERO failed
       client responses for requests scheduled after the ejection window
       (connection-refused ejects on the spot and retries mask the rest),
       goodput after the window within 5% of pre-kill, and the victim back
       routable through supervised restart (launches >= 2) — warm, via the
       Phase-A cache.
    """
    import shutil
    import signal as _signal
    import subprocess
    import sys
    import tempfile
    import urllib.request

    import jax

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.serve.tier import (ReplicaHandle, TierRouter,
                                           free_port)

    platform = jax.devices()[0].platform
    boot_model = os.environ.get("DEEPVISION_SERVE_BENCH_TIER_BOOT_MODEL",
                                "yolov3_digits")
    kill_model = os.environ.get("DEEPVISION_SERVE_BENCH_MODEL", "lenet5")
    cache_dir = tempfile.mkdtemp(prefix="deepvision-tier-bench-cache-")
    # without this, sub-second bucket compiles stay below JAX's default
    # persistence threshold and the "warm" boot recompiles everything
    replica_env = {"DEEPVISION_CACHE_MIN_COMPILE_SECS": "0"}

    def payload(model: str) -> bytes:
        d = get_config(model).data
        row = [[0.5] * d.channels for _ in range(d.image_size)]
        inst = [row for _ in range(d.image_size)]
        return json.dumps({"instances": [inst]}).encode()

    def replica_argv(model, port, rid, extra=()):
        return [sys.executable, "-m", "deepvision_tpu.serve.replica",
                "-m", model, "--port", str(port), "--host", "127.0.0.1",
                "--replica-id", rid, "--compilation-cache", cache_dir,
                *extra]

    def boot_to_first_200(rid: str):
        """(seconds Popen -> first /predict 200, compile stats) for one
        replica booted against the shared cache dir, then killed."""
        port = free_port()
        body = payload(boot_model)
        env = dict(os.environ)
        env.update(replica_env)
        t0 = time.monotonic()
        proc = subprocess.Popen(
            replica_argv(boot_model, port, rid,
                         ("--buckets", "1,8", "--max-batch", "8")),
            env=env)
        url = f"http://127.0.0.1:{port}/predict"
        try:
            while True:
                if proc.poll() is not None:
                    raise SystemExit(
                        f"tier bench: boot replica {rid} exited "
                        f"{proc.returncode} before its first 200")
                if time.monotonic() - t0 > 300:
                    raise SystemExit(
                        f"tier bench: boot replica {rid} gave no 200 "
                        f"within 300 s")
                try:
                    req = urllib.request.Request(
                        url, data=body,
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=30) as r:
                        if r.status == 200:
                            r.read()
                            break
                except Exception:  # noqa: BLE001 — booting: not up yet
                    time.sleep(0.05)
            boot_s = time.monotonic() - t0
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz",
                                        timeout=10) as r:
                health = json.loads(r.read().decode())
            compile_stats = (health.get("models", {}).get(boot_model)
                             or {}).get("compile") or {}
        finally:
            proc.kill()
            proc.wait()
        return boot_s, compile_stats

    try:
        # -- phase A: warm-vs-cold boot through the shared compile cache --
        cold_s, cold_compile = boot_to_first_200("bench-cold")
        warm_s, warm_compile = boot_to_first_200("bench-warm")
        speedup = cold_s / warm_s if warm_s else 0.0
        warm_zero_recompiles = (warm_compile.get("cache_misses", -1) == 0
                                and warm_compile.get("cache_hits", 0) > 0)

        # -- phase B: kill one of three under an open-loop schedule --------
        handles = []
        for slot in range(3):
            port = free_port()
            handles.append(ReplicaHandle(
                f"bench-{slot}", f"http://127.0.0.1:{port}",
                argv=replica_argv(kill_model, port, f"bench-{slot}"),
                env=replica_env, slot=slot))
        router = TierRouter(handles, health_every_s=0.15,
                            probe_timeout_s=1.0, restart_backoff_s=0.3)
        router.start()
        try:
            if not router.wait_ready(n=3, timeout=240):
                raise SystemExit(
                    "tier bench: 3 replicas never became routable")
            total = max(6.0, args.secs * 3)
            qps = args.qps or 25.0
            eject_window_s = 1.5
            n_req = int(total * qps)
            url = f"http://127.0.0.1:{router.bound_port}/predict"
            body = payload(kill_model)
            results: list = [None] * n_req
            start = time.monotonic()

            def client(w: int, n_workers: int) -> None:
                # open-loop: arrival i fires at i/qps on the shared clock,
                # never gated on the previous completion
                for i in range(w, n_req, n_workers):
                    t_sched = i / qps
                    lag = t_sched - (time.monotonic() - start)
                    if lag > 0:
                        time.sleep(lag)
                    try:
                        req = urllib.request.Request(
                            url, data=body,
                            headers={"Content-Type": "application/json",
                                     "X-Deadline-Ms": "15000"})
                        with urllib.request.urlopen(req, timeout=20) as r:
                            ok = r.status == 200
                            r.read()
                    except Exception:  # noqa: BLE001 — a failure IS data
                        ok = False
                    results[i] = (t_sched, ok)

            n_workers = 16
            threads = [threading.Thread(target=client, args=(w, n_workers),
                                        daemon=True)
                       for w in range(n_workers)]
            for t in threads:
                t.start()
            victim = handles[0]
            while time.monotonic() - start < total / 3.0:
                time.sleep(0.02)
            proc = victim.proc
            if proc is not None:
                proc.send_signal(_signal.SIGKILL)
            kill_at = time.monotonic() - start
            for t in threads:
                t.join()
            # supervised readmission: backoff + warm boot off the shared
            # cache; must come back routable on its own
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not (
                    victim.routable and victim.launches >= 2):
                time.sleep(0.1)
            readmitted = victim.routable and victim.launches >= 2
            stats = dict(router.stats)
            victim_desc = victim.describe()
        finally:
            router.close(replica_grace_s=10)

        done = [r for r in results if r is not None]
        pre = [r for r in done if r[0] < kill_at]
        window = [r for r in done
                  if kill_at <= r[0] < kill_at + eject_window_s]
        post = [r for r in done if r[0] >= kill_at + eject_window_s]
        pre_good = sum(1 for r in pre if r[1]) / max(1, len(pre))
        post_good = sum(1 for r in post if r[1]) / max(1, len(post))
        failed_window = sum(1 for r in window if not r[1])
        failed_after = sum(1 for r in post if not r[1])

        print(json.dumps({
            "metric": f"serve_tier_warm_boot_speedup({boot_model},"
                      f"shared-xla-cache,{platform})",
            "value": round(speedup, 2),
            "unit": "x (cold boot-to-first-200 / warm)",
            "vs_baseline": round(speedup, 2),
            "baseline": "cold replica boot (empty persistent compile "
                        "cache) to first /predict 200, identical argv",
            "boot_model": boot_model,
            "cold_boot_s": round(cold_s, 2),
            "warm_boot_s": round(warm_s, 2),
            "cold_compile": cold_compile,
            "warm_compile": warm_compile,
            "warm_zero_recompiles": warm_zero_recompiles,
            "kill_one": {
                "model": kill_model,
                "replicas": 3,
                "offered_qps": qps,
                "offered_requests": n_req,
                "answered": len(done),
                "kill_at_s": round(kill_at, 2),
                "eject_window_s": eject_window_s,
                "goodput_pre_kill": round(pre_good, 4),
                "goodput_post_window": round(post_good, 4),
                "failed_in_window": failed_window,
                "failed_after_window": failed_after,
                "ejections": stats.get("ejections", 0),
                "readmissions": stats.get("readmissions", 0),
                "restarts": stats.get("restarts", 0),
                "retries": stats.get("retries", 0),
                "victim_launches": victim_desc["launches"],
                "victim_readmitted": readmitted,
            },
            "secs": args.secs,
            "cpu_cores": os.cpu_count(),
            "platform": platform,
        }))
        bars = []
        if not warm_zero_recompiles:
            bars.append(f"warm boot recompiled: {warm_compile}")
        if speedup < 2.0:
            bars.append(f"warm boot speedup {speedup:.2f}x < 2x "
                        f"(cold {cold_s:.2f}s, warm {warm_s:.2f}s)")
        if failed_after:
            bars.append(f"{failed_after} failed responses after the "
                        f"{eject_window_s:g}s ejection window")
        if post_good < 0.95 * pre_good:
            bars.append(f"post-kill goodput {post_good:.3f} fell >5% under "
                        f"pre-kill {pre_good:.3f}")
        if not readmitted:
            bars.append("victim never re-admitted by supervised restart")
        if bars:
            raise SystemExit("tier bench bars broke: " + "; ".join(bars))
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--int8", action="store_true",
                   help="int8-vs-bf16 comparison: arm the calibrated "
                        "quantization gate on the bench model, then drive "
                        "the same closed-loop load through each precision "
                        "ladder — sustained QPS, p99, bytes/batch as one "
                        "bench line (docs/SERVING.md 'Quantized serving')")
    p.add_argument("--mesh", action="store_true",
                   help="mesh-sharded (GSPMD) predict vs the single-chip "
                        "engine: per-chip resident weight bytes (bar: cut "
                        ">= 0.98x the model-axis size), p99 at batch-max, "
                        "largest config servable per chip HBM budget each "
                        "way, and the zero-recompile-across-a-promotion "
                        "proof — run on CPU virtual devices (XLA_FLAGS="
                        "--xla_force_host_platform_device_count=8; make "
                        "bench-serve-mesh) — docs/SERVING.md 'Mesh serving'")
    p.add_argument("--model-parallel", type=int, default=2,
                   help="--mesh: model-axis size of the serve mesh "
                        "(default 2)")
    p.add_argument("--spatial-parallel", type=int, default=1,
                   help="--mesh: spatial-axis size of the serve mesh "
                        "(default 1)")
    p.add_argument("--hbm-gb", type=float, default=0.0625, metavar="GIB",
                   help="--mesh: per-chip HBM budget for the "
                        "largest-servable scan (default 0.0625 = 64 MiB — "
                        "small enough that the registry's largest models "
                        "only fit model-parallel)")
    p.add_argument("--tier", action="store_true",
                   help="replica-tier bench (serve/tier.py): warm-vs-cold "
                        "replica boot-to-first-200 through the shared "
                        "persistent compile cache (bar: >=2x, zero warm "
                        "recompiles), then SIGKILL one of 3 supervised "
                        "replicas under an open-loop schedule (bars: zero "
                        "failed responses after the ejection window, "
                        "goodput within 5%% of pre-kill, supervised "
                        "readmission) — docs/SERVING.md 'Replica tier'")
    p.add_argument("--flywheel", action="store_true",
                   help="serve->train->serve flywheel bench "
                        "(flywheel/): arm the DRIFT_SHIFT fault under "
                        "closed-loop load and measure time-to-detect, "
                        "time-to-promoted, and serving goodput through "
                        "the drift->fine-tune->gate->promote episode "
                        "(bars: zero failed responses, zero shed, zero "
                        "serve-path recompiles) — docs/FAILURES.md "
                        "'Flywheel decisions'")
    p.add_argument("--load", action="store_true",
                   help="open-loop fleet load bench (sustained-QPS arrival "
                        "schedule over --models) instead of the closed-loop "
                        "single-model throughput bench")
    p.add_argument("--models",
                   default=os.environ.get("DEEPVISION_SERVE_BENCH_FLEET",
                                          "lenet5,lenet5_digits"),
                   help="comma-separated fleet for --load (default "
                        "lenet5,lenet5_digits — two models, CPU-cheap)")
    p.add_argument("--qps", type=float, default=0.0,
                   help="offered arrival rate for --load (default 0 = auto: "
                        "70%% of the measured fleet capacity estimate)")
    p.add_argument("--secs", type=float,
                   default=float(os.environ.get("DEEPVISION_SERVE_BENCH_SECS",
                                                "2.0")),
                   help="arrival-schedule duration for --load")
    p.add_argument("--max-batch", type=int,
                   default=int(os.environ.get(
                       "DEEPVISION_SERVE_BENCH_MAX_BATCH", "32")))
    p.add_argument("--delay-ms", type=float, default=None,
                   help="micro-batching deadline (default 5; 10 with "
                        "--promote-at — the promotion bench runs at a "
                        "healthy operating point, where the p99 floor is "
                        "the deadline, not queueing)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="with --load: run the arrival schedule twice — "
                        "untraced, then with span tracing at default "
                        "sampling — dump the traced run's Perfetto/Chrome "
                        "trace JSON to PATH, and FAIL (exit nonzero) if "
                        "tracing cost more than 3%% of sustained QPS "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--spike", action="store_true",
                   help="with --load: bench the overload TRANSIENT instead "
                        "of steady state — offered QPS steps 1x -> 3x -> 1x "
                        "while the shed-driven autoscaler scales the "
                        "dispatcher pools; reports time-to-absorb, shed "
                        "during the transient, per-phase p99, and the "
                        "zero-recompile worker-spawn proof (docs/SERVING.md "
                        "'Overload control')")
    p.add_argument("--max-workers", type=int, default=4,
                   help="--spike: autoscale ceiling per model (default 4)")
    p.add_argument("--promote-at", type=float, default=0.0, metavar="SECS",
                   help="with --load: commit a new checkpoint epoch at SECS "
                        "into the arrival schedule and run the full "
                        "accuracy-gated shadow->canary->promote cycle under "
                        "load (docs/SERVING.md 'Promotion'); 0 disables. "
                        "Arm DEEPVISION_FAULT_PROMOTE_REGRESS=2:<kind> to "
                        "bench the auto-rollback instead")
    p.add_argument("--canary-frac", type=float, default=0.2,
                   help="--promote-at: canary traffic fraction (default 0.2)")
    p.add_argument("--canary-window", type=float, default=1.0,
                   help="--promote-at: canary decision window seconds "
                        "(default 1)")
    args = p.parse_args(argv)
    if args.int8 and (args.load or args.spike or args.promote_at
                      or args.trace_out):
        raise SystemExit("--int8 is the standalone precision comparison — "
                         "run it without the --load family of modes")
    if args.tier and (args.int8 or args.load or args.spike
                      or args.promote_at or args.trace_out):
        raise SystemExit("--tier is the standalone replica-tier bench — "
                         "run it without the other modes")
    if args.mesh and (args.int8 or args.tier or args.load or args.spike
                      or args.promote_at or args.trace_out):
        raise SystemExit("--mesh is the standalone mesh-vs-single-chip "
                         "bench — run it without the other modes")
    if args.mesh and (args.model_parallel < 1 or args.spatial_parallel < 1):
        raise SystemExit("--model-parallel/--spatial-parallel must be >= 1")
    if args.flywheel and (args.int8 or args.tier or args.mesh or args.load
                          or args.spike or args.promote_at
                          or args.trace_out):
        raise SystemExit("--flywheel is the standalone drift->retrain->"
                         "promote bench — run it without the other modes")
    if args.promote_at and not args.load:
        raise SystemExit("--promote-at needs --load (the promotion bench "
                         "runs under the open-loop arrival schedule)")
    if args.spike and not args.load:
        raise SystemExit("--spike needs --load (the transient bench runs "
                         "under the open-loop arrival schedule)")
    if args.spike and args.promote_at:
        raise SystemExit("--spike and --promote-at are separate benches — "
                         "run them one at a time")
    if args.trace_out and (not args.load or args.spike or args.promote_at):
        raise SystemExit("--trace-out needs the plain --load bench (the "
                         "overhead comparison runs the steady arrival "
                         "schedule twice)")
    if args.delay_ms is None:
        env_delay = os.environ.get("DEEPVISION_SERVE_BENCH_DELAY_MS")
        args.delay_ms = (float(env_delay) if env_delay
                         else 10.0 if args.promote_at else 5.0)
    if args.int8:
        int8_bench()
    elif args.flywheel:
        flywheel_bench(args)
    elif args.mesh:
        mesh_bench(args)
    elif args.tier:
        tier_bench(args)
    elif args.load and args.promote_at:
        promote_under_load(args)
    elif args.load and args.spike:
        spike_bench(args)
    elif args.load:
        open_loop(args)
    else:
        closed_loop()


if __name__ == "__main__":
    main()
