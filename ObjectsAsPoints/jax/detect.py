#!/usr/bin/env python
"""CenterNet inference: restore a checkpoint, detect objects in images, print
boxes — completing the inference surface the reference's WIP family never
shipped (`ObjectsAsPoints/tensorflow/train.py:248` disabled runner; no
inference script or README upstream). Peak-pick decode replaces NMS
(paper §3 via `ops/centernet.py:decode`).

Usage: python detect.py --workdir runs/centernet image1.jpg ...
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--workdir", default=None,
                   help="training workdir holding ckpt/ (default runs/<model>)")
    p.add_argument("--score-thresh", type=float, default=0.3)
    p.add_argument("--max-detections", type=int, default=100)
    p.add_argument("--image-size", type=int, default=None,
                   help="inference resolution (default: the config's)")
    p.add_argument("images", nargs="+")
    args = p.parse_args(argv)

    import jax.numpy as jnp
    import numpy as np
    from PIL import Image

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.centernet import (CenterNetTrainer,
                                               make_centernet_predict_step)

    cfg = get_config("centernet")
    trainer = CenterNetTrainer(
        cfg, workdir=args.workdir or os.path.join("runs", cfg.name))
    size = args.image_size or cfg.data.image_size
    trainer.init_state((size, size, 3))
    if trainer.resume() is None:
        print("WARNING: no checkpoint found — using random weights")

    predict = make_centernet_predict_step(max_detections=args.max_detections)
    from deepvision_tpu.data.class_names import names_for
    names = names_for(cfg.data.num_classes)

    # fixed-size chunks (last one zero-padded): one compiled shape, flat
    # memory however many images are passed
    chunk = 8
    for start in range(0, len(args.images), chunk):
        paths = args.images[start:start + chunk]
        batch = np.zeros((chunk, size, size, 3), np.float32)
        for j, path in enumerate(paths):
            img = Image.open(path).convert("RGB").resize((size, size))
            batch[j] = np.asarray(img, np.float32) / 127.5 - 1.0
        boxes, scores, classes = map(np.asarray,
                                     predict(trainer.eval_state(), jnp.asarray(batch)))
        for i, path in enumerate(paths):
            keep = scores[i] >= args.score_thresh  # scores are top-k descending
            n = int(keep.sum())
            print(f"{path}: {n} detections")
            for d in range(n):
                x1, y1, x2, y2 = boxes[i, d]
                print(f"  {names[int(classes[i, d])]} score={scores[i, d]:.3f} "
                      f"box=({x1:.3f},{y1:.3f},{x2:.3f},{y2:.3f})")
    trainer.close()


if __name__ == "__main__":
    main()
