#!/usr/bin/env python
"""Train CenterNet (ObjectsAsPoints) on TPU — `python train.py -m centernet` (alias: `objects_as_points`).

The reference left this family disabled (`ObjectsAsPoints/tensorflow/train.py:35,248`
— empty loss list, commented-out runner); this entrypoint runs the completed
TPU-native implementation (focal + L1 losses, on-device gaussian heatmap labels).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from deepvision_tpu.cli import run_centernet

MODELS = ["centernet", "objects_as_points", "centernet_digits"]

if __name__ == "__main__":
    run_centernet("ObjectsAsPoints", MODELS)
