#!/usr/bin/env python
"""CenterNet mAP evaluation on the val split — past where the reference's WIP
family stopped (`ObjectsAsPoints/tensorflow/train.py:248` disabled runner).

Usage:
    python evaluate.py --data-dir dataset/tfrecords --metric coco
    python evaluate.py --synthetic           # smoke, random weights
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-m", "--model", default="centernet",
                   help="registered config name (centernet, centernet_digits)")
    p.add_argument("-c", "--checkpoint", default="latest")
    p.add_argument("--workdir", default=None)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--metric", default="coco", choices=["coco", "voc", "voc07"])
    p.add_argument("--score-thresh", type=float, default=0.05)
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--max-batches", type=int, default=None)
    p.add_argument("--out", default=None,
                   help="also write the metrics dict as JSON (artifact use)")
    args = p.parse_args(argv)

    import itertools

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.centernet import CenterNetTrainer, evaluate_map

    cfg = get_config(args.model)
    trainer = CenterNetTrainer(
        cfg, workdir=args.workdir or os.path.join("runs", cfg.name))
    size = 128 if args.synthetic else cfg.data.image_size
    trainer.init_state((size, size, 3))
    if not args.synthetic and trainer.resume(
            None if args.checkpoint == "latest" else int(args.checkpoint)) is None:
        print("WARNING: no checkpoint found — evaluating random weights")

    if args.synthetic:
        from deepvision_tpu.data.detection import synthetic_batches
        batches = synthetic_batches(batch_size=2, image_size=size,
                                    num_classes=cfg.data.num_classes, steps=2)
    elif cfg.data.dataset == "digits_detect":
        # the real-scanned-digits detection gate: eval over the held-out
        # val scenes (data/digits.py — scans never seen in training; same
        # seed-2 identity the training CLI pins)
        from deepvision_tpu.data.digits import (detection_batches,
                                                detection_val_scenes)
        va = detection_val_scenes(canvas=cfg.data.image_size,
                                 n_scenes=cfg.data.val_examples)
        batches = detection_batches(va, batch_size=cfg.batch_size)
    else:
        from deepvision_tpu.data.detection import build_dataset
        data_dir = args.data_dir or cfg.data.data_dir or "dataset/tfrecords"
        ds = build_dataset(os.path.join(data_dir, "val*"),
                           batch_size=cfg.batch_size, image_size=size,
                           training=False, with_difficult=True,
                           drop_remainder=False)
        batches = (tuple(t.numpy() for t in b) for b in ds)
    if args.max_batches:
        batches = itertools.islice(batches, args.max_batches)

    metrics = evaluate_map(trainer.eval_state(), batches,
                           num_classes=cfg.data.num_classes,
                           metric=args.metric, score_thresh=args.score_thresh)
    trainer.close()
    for k in sorted(metrics):
        if k.startswith("mAP"):
            print(f"{k}: {metrics[k]:.4f}")
    if args.out:
        import json
        with open(args.out, "w") as fp:
            json.dump({k: float(v) for k, v in metrics.items()}, fp,
                      indent=1, sort_keys=True)
            fp.write("\n")
    return metrics


if __name__ == "__main__":
    main()
