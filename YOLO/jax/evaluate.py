#!/usr/bin/env python
"""YOLO V3 accuracy evaluation: COCO mAP@[.5:.95] / VOC mAP@0.5 on the val split.

The reference never shipped this — its README lists mAP as "work in progress"
(`YOLO/tensorflow/README.md:29`). Usage:

    python evaluate.py -m yolov3_voc --data-dir dataset/tfrecords --metric voc
    python evaluate.py -m yolov3 --synthetic            # smoke, random weights
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("-m", "--model", default="yolov3",
                   choices=["yolov3", "yolov3_voc", "yolov3_digits"])
    p.add_argument("-c", "--checkpoint", default="latest",
                   help="epoch number or 'latest'")
    p.add_argument("--workdir", default=None)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--metric", default="coco", choices=["coco", "voc", "voc07"])
    p.add_argument("--score-thresh", type=float, default=0.05)
    p.add_argument("--iou-thresh", type=float, default=0.5,
                   help="NMS IoU threshold (not the matching threshold)")
    p.add_argument("--synthetic", action="store_true",
                   help="evaluate on synthetic batches (smoke test)")
    p.add_argument("--max-batches", type=int, default=None)
    p.add_argument("--out", default=None,
                   help="also write the metrics dict as JSON (artifact use)")
    args = p.parse_args(argv)

    import itertools

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.detection import DetectionTrainer, evaluate_map

    cfg = get_config(args.model)
    trainer = DetectionTrainer(
        cfg, workdir=args.workdir or os.path.join("runs", cfg.name))
    size = 64 if args.synthetic else cfg.data.image_size
    trainer.init_state((size, size, 3))
    if not args.synthetic and trainer.resume(
            None if args.checkpoint == "latest" else int(args.checkpoint)) is None:
        print("WARNING: no checkpoint found — evaluating random weights")

    if args.synthetic:
        from deepvision_tpu.data.detection import synthetic_batches
        batches = synthetic_batches(batch_size=4, image_size=size,
                                    num_classes=cfg.data.num_classes, steps=2)
    elif cfg.data.dataset == "digits_detect":
        # the real-scanned-digits detection gate (data/digits.py): held-out
        # val scenes, same seed-2 identity the training CLI pins
        from deepvision_tpu.data.digits import (detection_batches,
                                                detection_val_scenes)
        va = detection_val_scenes(canvas=cfg.data.image_size,
                                 n_scenes=cfg.data.val_examples)
        batches = detection_batches(va, batch_size=cfg.batch_size)
    else:
        from deepvision_tpu.data.detection import build_dataset
        data_dir = args.data_dir or cfg.data.data_dir or "dataset/tfrecords"
        # keep the val tail (drop_remainder=False) and carry difficult flags —
        # both required for protocol-faithful numbers
        ds = build_dataset(os.path.join(data_dir, "val*"),
                           batch_size=cfg.batch_size, image_size=size,
                           training=False, with_difficult=True,
                           drop_remainder=False)
        batches = (tuple(t.numpy() for t in b) for b in ds)
    if args.max_batches:
        batches = itertools.islice(batches, args.max_batches)

    metrics = evaluate_map(trainer.eval_state(), batches,
                           num_classes=cfg.data.num_classes, metric=args.metric,
                           iou_thresh=args.iou_thresh,
                           score_thresh=args.score_thresh)
    trainer.close()
    for k in sorted(metrics):
        if k.startswith("mAP"):
            print(f"{k}: {metrics[k]:.4f}")
    if args.out:
        import json
        with open(args.out, "w") as fp:
            json.dump({k: float(v) for k, v in metrics.items()}, fp,
                      indent=1, sort_keys=True)
            fp.write("\n")
    return metrics


if __name__ == "__main__":
    main()
