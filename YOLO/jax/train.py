#!/usr/bin/env python
"""Train YOLO V3 on TPU — `python train.py -m yolov3|yolov3_voc [-c latest]`.

Per-family entrypoint matching the reference's UX (`YOLO/tensorflow/train.py:276-313`:
`python3 train.py --checkpoint <ckpt>`), backed by the shared deepvision_tpu
DetectionTrainer instead of the MirroredStrategy loop.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from deepvision_tpu.cli import run_detection

MODELS = ["yolov3", "yolov3_voc", "yolov3_digits"]

if __name__ == "__main__":
    run_detection("YOLO", MODELS)
