#!/usr/bin/env python
"""YOLO V3 inference: restore a checkpoint, detect objects in images, print/save
boxes — the role of the reference's demo notebook + `Postprocessor`
(`YOLO/tensorflow/demo_mscoco.ipynb`, `postprocess.py:6-36`).

Usage: python detect.py -m yolov3 --workdir runs/yolov3 image1.jpg ...
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("-m", "--model", default="yolov3",
                   choices=["yolov3", "yolov3_voc"])
    p.add_argument("--workdir", default=None,
                   help="training workdir holding ckpt/ (default runs/<model>)")
    p.add_argument("--iou-thresh", type=float, default=0.5)
    p.add_argument("--score-thresh", type=float, default=0.5)
    p.add_argument("--image-size", type=int, default=416)
    p.add_argument("images", nargs="+")
    args = p.parse_args()

    import jax.numpy as jnp
    import numpy as np
    from PIL import Image

    from deepvision_tpu.configs import get_config
    from deepvision_tpu.core.detection import DetectionTrainer, make_predict_step

    cfg = get_config(args.model)
    trainer = DetectionTrainer(
        cfg, workdir=args.workdir or os.path.join("runs", cfg.name))
    trainer.init_state((args.image_size, args.image_size, 3))
    if trainer.resume() is None:
        print("WARNING: no checkpoint found — using random weights")

    size = args.image_size
    # decoded per-scale outputs → flatten → NMS (`postprocess.py:12-36`)
    predict = make_predict_step(iou_thresh=args.iou_thresh,
                                score_thresh=args.score_thresh)
    from deepvision_tpu.data.class_names import names_for
    names = names_for(cfg.data.num_classes)

    # fixed-size chunks (last one zero-padded): one compiled shape, flat
    # memory however many images are passed
    chunk = 8
    for start in range(0, len(args.images), chunk):
        paths = args.images[start:start + chunk]
        batch = np.zeros((chunk, size, size, 3), np.float32)
        for j, path in enumerate(paths):
            img = Image.open(path).convert("RGB").resize((size, size))
            batch[j] = np.asarray(img, np.float32) / 127.5 - 1.0
        nms_boxes, nms_scores, nms_classes, counts = predict(
            trainer.eval_state(), jnp.asarray(batch))
        for i, path in enumerate(paths):
            n = int(counts[i])
            print(f"{path}: {n} detections")
            for d in range(n):
                x1, y1, x2, y2 = np.asarray(nms_boxes[i, d])
                cls = int(jnp.argmax(nms_classes[i, d]))
                print(f"  {names[cls]} score={float(nms_scores[i, d]):.3f} "
                      f"box=({x1:.3f},{y1:.3f},{x2:.3f},{y2:.3f})")
    trainer.close()


if __name__ == "__main__":
    main()
